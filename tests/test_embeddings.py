"""OpenAI ``/embeddings`` served from the chat models' resident weights.

Beyond-reference surface (the reference proxies only /chat/completions):
vectors are mean-pooled final-norm hidden states, L2-normalized, computed
on device by quorum_tpu/engine/embed.py. Pins here:

  - wire shape (object list / data / usage / backend tag) and unit norm;
  - padding independence: a text's vector is identical whether it is
    batched alone or beside a much longer input (causal attention + masked
    pooling — the correctness core of the bucketed batch path);
  - pre-tokenized inputs, dimensions truncation (truncate → renormalize),
    base64 encoding, member selection on stacked engines;
  - the documented 400/401/500 error families.
"""

import base64

import numpy as np
import pytest

from tests.conftest import make_client

# Engine-scale / compile-heavy: slow tier (make test skips, make test-all
# and CI run everything).
pytestmark = pytest.mark.slow

URL = "tpu://llama-tiny?seed=1&max_seq=256&slots=2&max_tokens=4"


def one_backend_config(url: str = URL, model: str = "tiny"):
    return {
        "settings": {"timeout": 300},
        "primary_backends": [
            {"name": "E1", "url": url, "model": model},
        ],
    }


async def post_embed(client, body):
    return await client.post("/v1/embeddings", json=body,
                             headers={"Authorization": "Bearer t"})


async def test_wire_shape_and_unit_norm():
    async with make_client(one_backend_config()) as client:
        resp = await post_embed(client, {"model": "tiny",
                                         "input": "hello embeddings"})
        assert resp.status_code == 200, resp.text
        got = resp.json()
        assert got["object"] == "list" and got["model"] == "tiny"
        assert got["backend"] == "E1"
        assert resp.headers.get("x-request-id")
        (item,) = got["data"]
        assert item["object"] == "embedding" and item["index"] == 0
        v = np.asarray(item["embedding"], np.float32)
        assert v.shape == (64,)  # llama-tiny d_model
        assert abs(float(np.linalg.norm(v)) - 1.0) < 1e-3
        usage = got["usage"]
        assert usage["prompt_tokens"] == usage["total_tokens"] > 0


async def test_padding_independence_and_determinism():
    """The same text embeds identically alone, co-batched beside a much
    longer input (different batch/seq buckets), and across calls."""
    async with make_client(one_backend_config()) as client:
        alone = (await post_embed(client, {"input": "anchor text"})).json()
        again = (await post_embed(client, {"input": "anchor text"})).json()
        batched = (await post_embed(client, {"input": [
            "anchor text",
            "a much longer companion input " * 6,
            "third",
        ]})).json()
        a = np.asarray(alone["data"][0]["embedding"], np.float32)
        b = np.asarray(again["data"][0]["embedding"], np.float32)
        c = np.asarray(batched["data"][0]["embedding"], np.float32)
        np.testing.assert_array_equal(a, b)
        np.testing.assert_allclose(a, c, atol=2e-5)
        assert [d["index"] for d in batched["data"]] == [0, 1, 2]
        # distinct texts get distinct directions
        other = np.asarray(batched["data"][1]["embedding"], np.float32)
        assert float(np.dot(a, other)) < 0.999


async def test_pretokenized_matches_text():
    async with make_client(one_backend_config()) as client:
        text = (await post_embed(client, {"input": "same bytes"})).json()
        # Recover the ids the byte tokenizer produced via a second request
        # shape: encode is deterministic, so embed the explicit id list.
        from quorum_tpu.engine.tokenizer import ByteTokenizer

        ids = ByteTokenizer(512).encode("same bytes")
        toks = (await post_embed(client, {"input": [ids]})).json()
        np.testing.assert_array_equal(
            np.asarray(text["data"][0]["embedding"], np.float32),
            np.asarray(toks["data"][0]["embedding"], np.float32))
        assert toks["usage"]["prompt_tokens"] == len(ids)


async def test_dimensions_truncates_then_renormalizes():
    async with make_client(one_backend_config()) as client:
        full = (await post_embed(client, {"input": "matryoshka"})).json()
        cut = (await post_embed(client, {"input": "matryoshka",
                                         "dimensions": 16})).json()
        f = np.asarray(full["data"][0]["embedding"], np.float32)
        c = np.asarray(cut["data"][0]["embedding"], np.float32)
        assert c.shape == (16,)
        expect = f[:16] / np.linalg.norm(f[:16])
        np.testing.assert_allclose(c, expect, atol=1e-5)


async def test_base64_encoding_round_trips():
    async with make_client(one_backend_config()) as client:
        flt = (await post_embed(client, {"input": "encode me"})).json()
        b64 = (await post_embed(client, {"input": "encode me",
                                         "encoding_format": "base64"})).json()
        raw = base64.b64decode(b64["data"][0]["embedding"])
        decoded = np.frombuffer(raw, dtype="<f4")
        np.testing.assert_allclose(
            decoded, np.asarray(flt["data"][0]["embedding"], np.float32),
            atol=1e-6)


async def test_member_selection_matches_seed_engine():
    """member=1 of a stacked members=2 engine embeds with the SAME weights
    as a plain seed=1 engine — the in-jit member slice is exact."""
    stacked = one_backend_config(
        url="tpu://llama-tiny?seed=0&members=2&member=1&max_seq=256"
            "&slots=2&max_tokens=4")
    async with make_client(stacked) as client:
        sv = (await post_embed(client, {"input": "member check"})).json()
    async with make_client(one_backend_config(
            url="tpu://llama-tiny?seed=1&max_seq=256&slots=2&max_tokens=4"
    )) as client:
        pv = (await post_embed(client, {"input": "member check"})).json()
    np.testing.assert_allclose(
        np.asarray(sv["data"][0]["embedding"], np.float32),
        np.asarray(pv["data"][0]["embedding"], np.float32), atol=2e-5)


async def test_model_routing_picks_matching_backend():
    cfg = {
        "settings": {"timeout": 300},
        "primary_backends": [
            {"name": "A", "url": "tpu://llama-tiny?seed=1&max_seq=256",
             "model": "model-a"},
            {"name": "B", "url": "tpu://llama-tiny?seed=2&max_seq=256",
             "model": "model-b"},
        ],
    }
    async with make_client(cfg) as client:
        got = (await post_embed(client, {"model": "model-b",
                                         "input": "route me"})).json()
        assert got["backend"] == "B" and got["model"] == "model-b"
        default = (await post_embed(client, {"input": "route me"})).json()
        assert default["backend"] == "A"


@pytest.mark.parametrize("body,fragment", [
    ({"input": []}, "input"),
    ({"input": ""}, "input"),
    ({"input": ["ok", 5]}, "each 'input' item"),
    ({"input": ["ok", [5, 6]]}, "must not mix"),
    ({"input": [[999999]]}, "in-vocab"),
    ({"input": "x", "encoding_format": "binary"}, "encoding_format"),
    ({"input": "x", "dimensions": 0}, "dimensions"),
    ({"input": "x", "dimensions": 4096}, "dimensions"),
    ({"input": ["x"] * 65}, "at most 64"),
])
async def test_invalid_requests_400(body, fragment):
    async with make_client(one_backend_config()) as client:
        resp = await post_embed(client, {"model": "tiny", **body})
        assert resp.status_code == 400, resp.text
        err = resp.json()["error"]
        assert err["type"] == "invalid_request_error"
        assert fragment in err["message"]


async def test_auth_required(monkeypatch):
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    async with make_client(one_backend_config()) as client:
        resp = await client.post("/v1/embeddings", json={"input": "x"})
        assert resp.status_code == 401
        assert resp.json()["error"]["type"] == "auth_error"


async def test_http_backend_relays_embeddings():
    """http(s):// backends relay /embeddings upstream with the same
    model-override precedence and backend tagging as chat."""
    import json as _json

    import httpx

    from quorum_tpu.backends.http_backend import HttpBackend

    seen = {}

    def handler(request):
        seen["path"] = request.url.path
        seen["body"] = _json.loads(request.content)
        return httpx.Response(200, json={
            "object": "list",
            "data": [{"object": "embedding", "index": 0,
                      "embedding": [0.6, 0.8]}],
            "model": "cfg-model",
            "usage": {"prompt_tokens": 2, "total_tokens": 2}})

    client = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    be = HttpBackend("H", "http://up.example/v1", model="cfg-model",
                     client=client)
    res = await be.embed({"model": "req-model", "input": "x"},
                         {"Authorization": "Bearer k"}, 30)
    assert res.ok and res.body["backend"] == "H"
    assert seen["path"] == "/v1/embeddings"
    assert seen["body"]["model"] == "cfg-model"  # config overrides request
    await be.aclose()


async def test_no_capable_backend_500():
    from quorum_tpu.backends.fake import FakeBackend

    cfg = {"settings": {"timeout": 60},
           "primary_backends": [
               {"name": "F", "url": "http://fake.example", "model": "m"}]}
    async with make_client(cfg, F=FakeBackend("F", model="m")) as client:
        resp = await post_embed(client, {"input": "x"})
        assert resp.status_code == 500
        assert resp.json()["error"]["type"] == "configuration_error"


async def test_scoring_admission_gate_503(monkeypatch):
    """ADVICE r4: embed/score device forwards are admission-gated — with
    MAX_SCORE_INFLIGHT forwards occupying the device, the next request
    503s (same _overloaded contract as a full chat queue) instead of
    piling uncancellable device work against live decode."""
    import asyncio
    import threading

    import numpy as _np

    from quorum_tpu.engine import embed as embed_mod

    release = threading.Event()

    def blocked_embed(engine, token_lists, member=0):
        release.wait(timeout=30)
        return _np.ones((len(token_lists), 64), _np.float32)

    monkeypatch.setattr(embed_mod, "embed_token_batch", blocked_embed)
    async with make_client(one_backend_config()) as client:
        async def one():
            return await post_embed(client, {"input": "x"})

        tasks = [asyncio.create_task(one()) for _ in range(3)]
        # let all three reach the gate while the device threads block
        await asyncio.sleep(0.5)
        release.set()
        codes = sorted(r.status_code for r in await asyncio.gather(*tasks))
        assert codes == [200, 200, 503], codes
        err = next(r for r in [t.result() for t in tasks]
                   if r.status_code == 503).json()["error"]
        assert err["type"] == "overloaded_error"
        # slots freed: the next request is admitted again
        ok = await post_embed(client, {"input": "y"})
        assert ok.status_code == 200, ok.text


def test_tpu_backend_model_never_blank():
    """The no-fan-out endpoints' blank-model fallback assumes only
    http(s):// relays can be blank — a blank-model tpu backend would serve
    arbitrary requested names from unrelated local weights. Pinned: a
    config omitting `model` yields a tpu backend named by its model_id."""
    from quorum_tpu.backends.registry import build_registry
    from quorum_tpu.config import Config

    raw = {"settings": {"timeout": 30},
           "primary_backends": [
               {"name": "A",
                "url": "tpu://llama-tiny?seed=1&max_seq=64&slots=1"}]}
    reg = build_registry(Config(raw=raw))
    b = reg.backends[0]
    try:
        assert b.model == b.model_id == "llama-tiny"
        assert b.model  # never blank
    finally:
        b.engine.shutdown()
