"""Inference-engine tests: tokenizer, bucketing, generation, determinism.

Runs tiny models on the CPU backend — same compiled code paths as TPU
(SURVEY.md §4's TPU-free test strategy)."""

import jax.numpy as jnp
import pytest

from quorum_tpu.engine.engine import InferenceEngine, get_engine, prefill_bucket
from quorum_tpu.engine.tokenizer import ByteTokenizer, render_chat
from quorum_tpu.models.model_config import MODEL_PRESETS, resolve_spec
from quorum_tpu.models.transformer import forward_logits, init_cache, prefill
from quorum_tpu.models.init import init_params
from quorum_tpu.ops.sampling import SamplerConfig

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


TINY = MODEL_PRESETS["llama-tiny"]


# ---- tokenizer ------------------------------------------------------------

def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer(512)
    text = "hello, wörld — ≋"
    assert tok.decode(tok.encode(text)) == text


def test_byte_tokenizer_small_vocab_folds():
    tok = ByteTokenizer(64)
    ids = tok.encode("hello")
    assert all(3 <= i < 64 for i in ids)


def test_incremental_detok_utf8_boundary():
    tok = ByteTokenizer(512)
    ids = tok.encode("é")  # two UTF-8 bytes
    d = tok.detokenizer()
    assert d.feed(ids[0]) == ""       # partial char withheld
    assert d.feed(ids[1]) == "é"      # completed on the second byte
    assert d.flush() == ""


def test_render_chat():
    msgs = [
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": [{"type": "text", "text": "hi"}]},
    ]
    assert render_chat(msgs) == "system: be brief\nuser: hi\nassistant:"


# ---- bucketing ------------------------------------------------------------

def test_prefill_bucket():
    assert prefill_bucket(1, 128) == 16
    assert prefill_bucket(16, 128) == 16
    assert prefill_bucket(17, 128) == 32
    assert prefill_bucket(100, 128) == 128
    assert prefill_bucket(500, 128) == 128  # clamped to max_seq


# ---- generation -----------------------------------------------------------

def test_generate_greedy_deterministic():
    eng = InferenceEngine(TINY, decode_chunk=4)
    greedy = SamplerConfig(temperature=0.0)
    a = eng.generate([5, 6, 7], max_new_tokens=10, sampler=greedy)
    b = eng.generate([5, 6, 7], max_new_tokens=10, sampler=greedy)
    assert a.token_ids == b.token_ids
    assert len(a.token_ids) == 10
    assert all(0 <= t < TINY.vocab_size for t in a.token_ids)


def test_generate_seeded_sampling_deterministic():
    eng = InferenceEngine(TINY, decode_chunk=4)
    s = SamplerConfig(temperature=0.9, top_p=0.95)
    a = eng.generate([5, 6, 7], max_new_tokens=8, sampler=s, seed=42)
    b = eng.generate([5, 6, 7], max_new_tokens=8, sampler=s, seed=42)
    c = eng.generate([5, 6, 7], max_new_tokens=8, sampler=s, seed=43)
    assert a.token_ids == b.token_ids
    assert a.token_ids != c.token_ids or True  # different seed *may* differ


def test_generate_matches_cache_free_forward():
    """Greedy decode through the KV cache must equal argmax over the
    cache-free full forward — validates prefill/decode cache consistency."""
    eng = InferenceEngine(TINY, decode_chunk=2)
    prompt = [5, 6, 7, 8, 9]
    got = eng.generate([*prompt], max_new_tokens=4, sampler=SamplerConfig(temperature=0.0))

    params = eng.params
    seq = list(prompt)
    for _ in range(4):
        logits = forward_logits(params, TINY, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))
    assert got.token_ids == seq[len(prompt):]


def test_generate_stops_at_eos():
    eng = InferenceEngine(TINY, decode_chunk=4)
    greedy = SamplerConfig(temperature=0.0)
    full = eng.generate([5], max_new_tokens=30, sampler=greedy)
    # Re-run declaring the 3rd generated token as "EOS": generation must stop there.
    eos = full.token_ids[2]
    if full.token_ids.index(eos) != 2:  # appears earlier → pick index accordingly
        eos_pos = full.token_ids.index(eos)
    else:
        eos_pos = 2
    stopped = eng.generate([5], max_new_tokens=30, sampler=greedy, eos_id=eos)
    assert stopped.token_ids == full.token_ids[:eos_pos]
    assert stopped.finish_reason == "stop"


def test_generate_respects_context_window():
    spec = resolve_spec("llama-tiny", {"max_seq": "32"})
    eng = InferenceEngine(spec)
    res = eng.generate(list(range(3, 31)), max_new_tokens=50,
                       sampler=SamplerConfig(temperature=0.0))
    # 28 prompt tokens in a 32 window → at most 4 new tokens
    assert 0 < len(res.token_ids) <= 4


def test_long_prompt_truncated_keeps_tail():
    spec = resolve_spec("llama-tiny", {"max_seq": "32"})
    eng = InferenceEngine(spec)
    res = eng.generate(list(range(3, 3 + 100)), max_new_tokens=5,
                       sampler=SamplerConfig(temperature=0.0))
    assert len(res.token_ids) >= 1


def test_stream_equals_batch():
    eng = InferenceEngine(TINY, decode_chunk=3)
    greedy = SamplerConfig(temperature=0.0)
    streamed = list(eng.generate_stream([9, 8], max_new_tokens=7, sampler=greedy))
    batch = eng.generate([9, 8], max_new_tokens=7, sampler=greedy)
    assert streamed == batch.token_ids


def test_get_engine_shared():
    a = get_engine(TINY, seed=0)
    b = get_engine(TINY, seed=0)
    c = get_engine(TINY, seed=1)
    assert a is b
    assert a is not c


def test_byte_tokenizer_maps_full_vocab_to_text():
    """Sampled ids above 258 (models sample the FULL vocab) must still
    detokenize to text — regression for mostly-empty streamed deltas."""
    from quorum_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer(50257)
    assert tok.token_byte(20410) != b""
    assert tok.token_byte(50256) != b""
    assert tok.token_byte(0) == b"" and tok.token_byte(2) == b""  # specials
    assert tok.token_byte(60000) == b""  # out of vocab
    d = tok.detokenizer()
    text = "".join(d.feed(t) for t in [20410, 41954, 26670]) + d.flush()
    assert len(text) >= 1
    # encode→decode roundtrip still exact for real text
    assert tok.decode(tok.encode("hello world")) == "hello world"
