"""Continuous-batching engine tests: co-batching, determinism, slot reuse.

The round-1 engine serialized concurrent requests behind a lock (VERDICT.md
weakness 4); the redesigned engine admits them into cache slots and decodes
them in one batched program. These tests pin the properties that redesign
must keep: results are independent of co-batching/slot assignment, requests
beyond the slot count queue and complete, abandoned requests release their
slot, and the per-row sampler matches the static-config sampler.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np

from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models.model_config import MODEL_PRESETS
from quorum_tpu.ops.sampling import SamplerConfig, sample_token, sample_token_rows

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

TINY = MODEL_PRESETS["llama-tiny"]


def _run_one(eng, seed, prompt, n=8, temp=0.8):
    return eng.generate(
        prompt, max_new_tokens=n,
        sampler=SamplerConfig(temperature=temp, top_p=0.9), seed=seed,
    ).token_ids


def test_concurrent_results_match_serial():
    """Co-batched generations must be byte-identical to serial ones —
    row-independent compute + per-request PRNG keys."""
    eng = InferenceEngine(TINY, decode_chunk=4, n_slots=4)
    jobs = [(seed, [3 + seed, 4, 5 + seed]) for seed in range(6)]
    serial = [_run_one(eng, s, p) for s, p in jobs]
    with ThreadPoolExecutor(max_workers=6) as ex:
        concurrent = list(ex.map(lambda job: _run_one(eng, *job), jobs))
    assert concurrent == serial


def test_more_requests_than_slots_all_complete():
    eng = InferenceEngine(TINY, decode_chunk=4, n_slots=2)
    with ThreadPoolExecutor(max_workers=5) as ex:
        results = list(ex.map(
            lambda seed: _run_one(eng, seed, [5, 6, 7], n=6), range(5)
        ))
    assert all(len(r) == 6 for r in results)
    assert all(all(0 <= t < TINY.vocab_size for t in r) for r in results)


def test_abandoned_stream_releases_slot():
    """Dropping the iterator early must free the slot for later requests."""
    eng = InferenceEngine(TINY, decode_chunk=2, n_slots=1)
    it = eng.generate_stream([5, 6], max_new_tokens=64,
                             sampler=SamplerConfig(temperature=0.0))
    next(it)
    it.close()  # abandon mid-generation
    res = eng.generate([7, 8], max_new_tokens=5,
                       sampler=SamplerConfig(temperature=0.0))
    assert len(res.token_ids) == 5


def test_concurrency_is_faster_than_serial():
    """Two co-batched generations should take well under 2x one generation —
    batched decode is the whole point of continuous batching. Generous
    threshold: even modest batching wins beat the 1.8x serial bound."""
    eng = InferenceEngine(TINY, decode_chunk=8, n_slots=4)
    _run_one(eng, 0, [3, 4, 5], n=24)  # compile prefill + decode programs

    t0 = time.perf_counter()
    _run_one(eng, 1, [3, 4, 5], n=24)
    one = time.perf_counter() - t0

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=2) as ex:
        list(ex.map(lambda s: _run_one(eng, s, [3, 4, 5], n=24), (2, 3)))
    two = time.perf_counter() - t0
    assert two < 1.8 * one, f"2 concurrent took {two:.3f}s vs 1 serial {one:.3f}s"


def test_cancel_event_stops_generation():
    eng = InferenceEngine(TINY, decode_chunk=2, n_slots=2)
    cancel = threading.Event()
    got = []
    for t in eng.generate_stream([5, 6], max_new_tokens=64,
                                 sampler=SamplerConfig(temperature=0.0),
                                 cancel=cancel):
        got.append(t)
        if len(got) == 3:
            cancel.set()
    assert 3 <= len(got) <= 3 + eng.decode_chunk


def test_engine_survives_failed_device_call():
    """A raising compiled call must fail the in-flight request AND leave the
    engine serviceable — the programs donate the cache/state buffers, so the
    scheduler has to rebuild device state after a failure (a poisoned request
    must not brick the shared engine)."""
    eng = InferenceEngine(TINY, decode_chunk=2, n_slots=2)

    real_decode_fn = eng._decode_fn
    calls = {"n": 0}

    def exploding_decode_fn(n_steps, want_lp=False, history=0):
        calls["n"] += 1
        if calls["n"] == 1:
            def boom(*a, **k):
                raise RuntimeError("injected device failure")
            return boom
        return real_decode_fn(n_steps, want_lp, history)

    eng._decode_fn = exploding_decode_fn
    try:
        eng.generate([5, 6], max_new_tokens=6,
                     sampler=SamplerConfig(temperature=0.0))
        raise AssertionError("expected the injected failure to surface")
    except RuntimeError as e:
        assert "injected" in str(e)

    res = eng.generate([5, 6], max_new_tokens=6,
                       sampler=SamplerConfig(temperature=0.0))
    assert len(res.token_ids) == 6


def test_sample_token_rows_matches_static_config():
    """Per-row sampler (array knobs) must agree with the static-config
    sampler on every deterministic setting, including mixed rows."""
    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(key, (4, 64)) * 3.0
    keys = jnp.stack([jax.random.PRNGKey(i) for i in range(4)])

    # greedy rows (temp<=0), top_k=1 rows, and tiny top_p rows all reduce to
    # argmax — deterministic regardless of key.
    out = sample_token_rows(
        logits, keys,
        temperature=jnp.array([0.0, 1.0, 1.0, 0.7]),
        top_p=jnp.array([1.0, 1.0, 0.01, 1.0]),
        top_k=jnp.array([0, 1, 0, 1], jnp.int32),
    )
    expect = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    assert np.array_equal(np.asarray(out), np.asarray(expect))

    # stochastic row: same key/knobs via the static path must land in the
    # same top-k support set.
    cfg = SamplerConfig(temperature=0.8, top_k=4)
    static = sample_token(logits[:1], jax.random.PRNGKey(7), cfg)
    rows = sample_token_rows(
        logits[:1], jax.random.PRNGKey(7)[None],
        temperature=jnp.array([0.8]), top_p=jnp.array([1.0]),
        top_k=jnp.array([4], jnp.int32),
    )
    topk_ids = set(np.asarray(jax.lax.top_k(logits[0], 4)[1]).tolist())
    assert int(static[0]) in topk_ids
    assert int(rows[0]) in topk_ids


def test_dispatch_overlap_engages_when_idle():
    """A long single-request generation with no admissions waiting must
    dispatch ahead of the read (the overlap counter proves the device is
    being fed chunk-to-chunk; the output itself is unchanged — state chains
    on device)."""
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models import resolve_spec
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = resolve_spec("llama-tiny", {"max_seq": "128"})
    eng = InferenceEngine(spec, decode_chunk=4)
    out = eng.generate([3, 5, 7], max_new_tokens=40,
                       sampler=SamplerConfig(temperature=0.0)).token_ids
    assert len(out) == 40
    # the first chunks compile their history buckets (overlap defers to the
    # compile guard); later chunks re-use warm programs and overlap
    assert eng.n_overlapped > 0
    assert eng.metrics()["overlapped_chunks_total"] == eng.n_overlapped
