"""The serving engine on a multi-device mesh (VERDICT r2 weakness 2).

Round 2 sharded the engine's params and slot cache but never executed the
engine itself on more than one device; the slot-indexed dynamic_update_slice
into a dp/tp-sharded donated cache is exactly the kind of program GSPMD can
reject or silently de-shard. These tests run the full continuous-batching
path — admission prefill into slots, batched decode chunks, per-row sampling
— on the virtual 8-device CPU mesh and pin the output to the single-device
engine token-for-token.
"""

import asyncio
from concurrent.futures import ThreadPoolExecutor

import pytest

from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.ops.sampling import SamplerConfig
from quorum_tpu.parallel import MeshConfig, make_mesh

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

TINY = resolve_spec("llama-tiny", {"n_kv_heads": "4"})


def _gen(eng, seed, prompt, n=8, temp=0.8):
    return eng.generate(
        prompt, max_new_tokens=n,
        sampler=SamplerConfig(temperature=temp, top_p=0.9), seed=seed,
    ).token_ids


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(tp=4),
    MeshConfig(dp=2, tp=4),
    MeshConfig(dp=2, sp=2, tp=2),
])
def test_mesh_engine_matches_single_device(mesh_cfg):
    """Greedy + sampled generations on a sharded engine must equal the
    single-device engine's output exactly (same seeds, same prompts)."""
    eng_1 = InferenceEngine(TINY, decode_chunk=4, n_slots=4)
    eng_m = InferenceEngine(TINY, make_mesh(mesh_cfg), decode_chunk=4, n_slots=4)
    jobs = [(seed, [3 + seed, 4, 5 + seed]) for seed in range(4)]
    single = [_gen(eng_1, s, p) for s, p in jobs]
    sharded = [_gen(eng_m, s, p) for s, p in jobs]
    assert sharded == single
    greedy_1 = eng_1.generate([7, 8, 9], max_new_tokens=8,
                              sampler=SamplerConfig(temperature=0.0)).token_ids
    greedy_m = eng_m.generate([7, 8, 9], max_new_tokens=8,
                              sampler=SamplerConfig(temperature=0.0)).token_ids
    assert greedy_m == greedy_1


def test_mesh_engine_concurrent_co_batching():
    """Continuous batching on the mesh: concurrent requests co-batch into one
    sharded decode program and still match serial results."""
    eng = InferenceEngine(TINY, make_mesh(MeshConfig(dp=2, tp=4)),
                          decode_chunk=4, n_slots=4)
    jobs = [(seed, [3 + seed, 4, 5 + seed]) for seed in range(6)]
    serial = [_gen(eng, s, p) for s, p in jobs]
    with ThreadPoolExecutor(max_workers=6) as ex:
        concurrent = list(ex.map(lambda job: _gen(eng, *job), jobs))
    assert concurrent == serial


def test_mesh_engine_slots_not_divisible_by_dp():
    """n_slots=3 on dp=2: cache batch axis can't shard — must replicate and
    still produce correct results."""
    eng_1 = InferenceEngine(TINY, decode_chunk=2, n_slots=3)
    eng_m = InferenceEngine(TINY, make_mesh(MeshConfig(dp=2, tp=2)),
                            decode_chunk=2, n_slots=3)
    assert _gen(eng_m, 1, [5, 6, 7]) == _gen(eng_1, 1, [5, 6, 7])


# De-quarantined (PR 17): the engine-path divergence was TWO stacked GSPMD
# miscompiles — the MoE concat-gather bug (see test_sharding.py) plus a
# second, MoE-independent one: batch-1 prefill (the engine's slot-mode
# admission) with the kv projection sharded at sub-head granularity
# (n_kv_heads=2 on tp=4 → half a KV head per device) produces wrong logits
# on dp=2×tp=4. Fixed by the GQA degrade rule in parallel/sharding.py:
# wk/wv replicate when n_kv_heads % tp != 0, mirroring kv_cache_sharding.
def test_moe_engine_on_mesh_matches_single_device():
    """Grouped sparse-MoE prefill (scatter/gather dispatch) + dense-MoE
    decode must survive GSPMD on a dp×tp(=ep) mesh inside the full engine
    path — experts shard over tp, the dispatch indices replicate."""
    moe = resolve_spec("mixtral-tiny")
    eng_1 = InferenceEngine(moe, decode_chunk=4, n_slots=2)
    eng_m = InferenceEngine(moe, make_mesh(MeshConfig(dp=2, tp=4)),
                            decode_chunk=4, n_slots=2)
    prompt = [(9 + 5 * i) % 500 for i in range(24)]
    for sampler, seed in ((SamplerConfig(temperature=0.0), 0),
                          (SamplerConfig(temperature=0.8, top_p=0.9), 5)):
        one = eng_1.generate(prompt, max_new_tokens=8, sampler=sampler,
                             seed=seed).token_ids
        sharded = eng_m.generate(prompt, max_new_tokens=8, sampler=sampler,
                                 seed=seed).token_ids
        assert sharded == one
        assert len(one) == 8


def test_stacked_members_on_mesh_match_single_device():
    """Stacked fan-out members on a tp mesh: the member axis vmaps OVER the
    sharded model call (params [M, …] with each member's leaves sharded),
    and every member's stream must still equal the unsharded members=1
    engine with that member's seed."""
    eng_m = InferenceEngine(TINY, make_mesh(MeshConfig(dp=2, tp=4)),
                            seed=0, members=2, decode_chunk=4, n_slots=2)
    singles = [InferenceEngine(TINY, seed=i, decode_chunk=4, n_slots=2)
               for i in range(2)]
    prompt = [3, 4, 5]
    want = [_gen(singles[i], 7, prompt) for i in range(2)]
    got = [
        eng_m.generate(prompt, max_new_tokens=8,
                       sampler=SamplerConfig(temperature=0.8, top_p=0.9),
                       seed=7, member=i).token_ids
        for i in range(2)
    ]
    assert got == want


def test_tpu_backend_with_tp_mesh():
    """A ``tpu://…&tp=4`` backend serves complete() and stream() through the
    sharded engine and matches the single-device backend's text."""
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    def build(url):
        return TpuBackend.from_spec(BackendSpec(
            name="tpu", url=url, model="tiny"))

    b_mesh = build("tpu://llama-tiny?n_kv_heads=4&tp=4&dp=2&seed=3")
    b_one = build("tpu://llama-tiny?n_kv_heads=4&seed=3")
    body = {
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 8,
        "temperature": 0.7,
        "seed": 11,
    }

    async def run(backend):
        res = await backend.complete(dict(body), {}, timeout=120)
        chunks = []
        async for c in backend.stream(dict(body) | {"stream": True}, {}, timeout=120):
            for ch in c.get("choices") or []:
                chunks.append((ch.get("delta") or {}).get("content") or "")
        return res.body["choices"][0]["message"]["content"], "".join(chunks)

    text_m, stream_m = asyncio.run(run(b_mesh))
    text_1, stream_1 = asyncio.run(run(b_one))
    assert text_m == text_1
    assert stream_m == stream_1
    assert text_m  # non-empty generation
