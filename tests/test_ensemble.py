"""On-device logit-ensemble decoding (``ensemble=M``, engine/engine.py).

The engine's member-vmapped decode must be EXACTLY equivalent to manually
averaging M independent models' next-token logits at every step — the
ensemble is a numerics contract, not a heuristic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models import init_params, resolve_spec
from quorum_tpu.models.transformer import forward_logits
from quorum_tpu.ops.sampling import SamplerConfig

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

SPEC = resolve_spec("llama-tiny", {"max_seq": "64"})
GREEDY = SamplerConfig(temperature=0.0)


def _manual_ensemble_rollout(seeds, prompt, n_new, transform=None):
    """Reference: full-context forward per member, average logits, argmax.
    ``transform`` (e.g. quantize_params) applies to each member's params."""
    members = [init_params(SPEC, s) for s in seeds]
    if transform is not None:
        members = [transform(p) for p in members]
    seq = list(prompt)
    out = []
    for _ in range(n_new):
        tokens = jnp.asarray([seq], jnp.int32)
        avg = sum(
            np.asarray(forward_logits(p, SPEC, tokens), np.float32)[0, -1]
            for p in members
        ) / len(members)
        nxt = int(avg.argmax())
        out.append(nxt)
        seq.append(nxt)
    return out


def test_ensemble_matches_manual_logit_average():
    eng = InferenceEngine(SPEC, decode_chunk=4, ensemble=2, seed=0)
    prompt = [3, 5, 7, 11]
    got = eng.generate(prompt, max_new_tokens=6, sampler=GREEDY).token_ids
    want = _manual_ensemble_rollout([0, 1], prompt, 6)
    assert got == want, (got, want)


def test_ensemble_differs_from_single_member():
    """The consensus stream is not just member 0's stream (the averaging is
    real)."""
    ens = InferenceEngine(SPEC, decode_chunk=4, ensemble=2, seed=10)
    solo = InferenceEngine(SPEC, decode_chunk=4, seed=10)
    prompt = [2, 4, 6, 8, 10]
    a = ens.generate(prompt, max_new_tokens=12, sampler=GREEDY).token_ids
    b = solo.generate(prompt, max_new_tokens=12, sampler=GREEDY).token_ids
    assert a != b


def test_ensemble_with_chunked_prefill_and_prefix_cache():
    """The segment/register path is member-vmapped too: long prompts and
    prefix reuse keep the exact consensus numerics."""
    eng = InferenceEngine(SPEC, decode_chunk=4, ensemble=2, seed=0,
                          prefill_chunk=16)
    prompt = [(3 + 7 * i) % 500 + 1 for i in range(40)]
    first = eng.generate(prompt, max_new_tokens=4, sampler=GREEDY).token_ids
    second = eng.generate(prompt, max_new_tokens=4, sampler=GREEDY).token_ids
    assert eng.prefix_hits == 1
    assert first == second
    want = _manual_ensemble_rollout([0, 1], prompt, 4)
    assert first == want


def test_ensemble_url_knob_and_rejections():
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    be = TpuBackend.from_spec(BackendSpec(
        name="E", url="tpu://llama-tiny?ensemble=2&max_seq=64&seed=5",
        model="m"))
    assert be.engine.ensemble == 2
    with pytest.raises(ValueError, match="one weight set"):
        InferenceEngine(SPEC, ensemble=2, params=init_params(SPEC, 0))


def test_ensemble_composes_with_int8():
    """quant=int8 + ensemble=M: each member quantizes independently inside
    the stacked init; the consensus equals manually averaging the two
    QUANTIZED members' logits."""
    from quorum_tpu.models.quant import quantize_params

    eng = InferenceEngine(SPEC, decode_chunk=4, ensemble=2, seed=0,
                          quant="int8")
    prompt = [3, 5, 7, 11]
    got = eng.generate(prompt, max_new_tokens=5, sampler=GREEDY).token_ids
    want = _manual_ensemble_rollout([0, 1], prompt, 5,
                                    transform=quantize_params)
    assert got == want, (got, want)


def test_ckpt_ensemble_rejected_before_load():
    from quorum_tpu.engine.engine import get_engine_from_ckpt

    with pytest.raises(ValueError, match="one weight set"):
        # raises before touching the (nonexistent) checkpoint path
        get_engine_from_ckpt("/does/not/exist", ensemble=2)
