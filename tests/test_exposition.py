"""Prometheus exposition conformance: the pure-Python promtool-style
validator (observability.validate_exposition) plus live /metrics checks —
histogram bucket monotonicity, _sum/_count consistency, and exactly one
# TYPE line per metric family. Run standalone via ``make metrics-check``."""

from quorum_tpu.observability import (
    DEFAULT_BUCKETS,
    Histogram,
    validate_exposition,
)
from tests.conftest import make_client

# ---- validator unit tests (no server, no jax) ------------------------------


def test_validator_accepts_reference_shapes():
    text = "\n".join([
        "# HELP demo_seconds a demo",
        "# TYPE demo_seconds histogram",
        'demo_seconds_bucket{le="0.1"} 1',
        'demo_seconds_bucket{le="1.0"} 3',
        'demo_seconds_bucket{le="+Inf"} 4',
        "demo_seconds_sum 2.5",
        "demo_seconds_count 4",
        "# TYPE demo_total counter",
        'demo_total{backend="LLM1",mode="a,b"} 7',
        "# TYPE demo_gauge gauge",
        "demo_gauge 3.14",
    ]) + "\n"
    assert validate_exposition(text) == []


def test_validator_flags_malformed_lines():
    bad = "\n".join([
        "# TYPE demo_total counter",
        "demo_total seven",           # non-numeric value
        'demo_total{unclosed="x" 1',  # unterminated label set
        "# TYPE demo_total counter",  # duplicate TYPE
    ]) + "\n"
    errors = validate_exposition(bad)
    assert any("non-numeric" in e for e in errors)
    assert any("malformed sample" in e for e in errors)
    assert any("duplicate TYPE" in e for e in errors)


def test_validator_flags_histogram_inconsistencies():
    text = "\n".join([
        "# TYPE h_seconds histogram",
        'h_seconds_bucket{le="0.1"} 5',
        'h_seconds_bucket{le="1.0"} 3',    # non-monotonic counts
        'h_seconds_bucket{le="+Inf"} 6',
        "h_seconds_sum 1.0",
        "h_seconds_count 7",               # != +Inf bucket
        "# TYPE g_seconds histogram",
        'g_seconds_bucket{le="0.5"} 2',    # no +Inf bucket
        "g_seconds_sum 0.5",
        "g_seconds_count 2",
    ]) + "\n"
    errors = validate_exposition(text)
    assert any("not monotonic" in e for e in errors)
    assert any("_count" in e and "+Inf" in e for e in errors)
    assert any("missing +Inf" in e for e in errors)


def test_validator_flags_type_after_samples():
    text = "\n".join([
        "late_total 1",
        "# TYPE late_total counter",
    ]) + "\n"
    assert any("after its samples" in e for e in validate_exposition(text))


def test_histogram_expose_is_valid_and_cumulative():
    h = Histogram("t_seconds", "t")
    for v in (0.002, 0.002, 0.3, 7.0, 1000.0):
        h.observe(v)
    h.observe(0.05, backend="A")
    text = "\n".join(h.expose()) + "\n"
    assert validate_exposition(text) == []
    snap = h.snapshot()
    unlabeled = snap[()]
    assert unlabeled["count"] == 5
    assert unlabeled["buckets"][-1] == 5          # +Inf holds everything
    assert abs(unlabeled["sum"] - 1007.304) < 1e-6
    # cumulative counts never decrease
    assert unlabeled["buckets"] == sorted(unlabeled["buckets"])
    labeled = snap[(("backend", "A"),)]
    assert labeled["count"] == 1


def test_labeled_gauge_and_route_series_expose_valid():
    """Gauge labels (ISSUE 14: per-stage decode occupancy) — each label
    set is its own last-writer-wins series under one TYPE line, the bare
    series survives for unlabeled writers, and the whole family (plus a
    route-labeled counter like kv_handoff_bytes) validates."""
    from quorum_tpu.telemetry.metrics import Counter, Gauge

    g = Gauge("demo_occupancy", "per-stage occupancy")
    g.set(3, stage="0")
    g.set(1, stage="1")
    g.set(2, stage="1")  # last writer wins per series
    lines = g.expose()
    assert 'demo_occupancy{stage="0"} 3.0' in lines
    assert 'demo_occupancy{stage="1"} 2.0' in lines
    assert "demo_occupancy 0.0" in lines  # bare series retained
    assert g.value_of(stage="0") == 3.0
    assert g.value == 0.0
    c = Counter("demo_bytes_total", "bytes by route")
    c.inc(10, route="reshard")
    c.inc(5, route="host-bounce")
    assert c.value == 15.0
    assert c.value_of(route="reshard") == 10.0
    text = "\n".join(g.expose() + c.expose()) + "\n"
    assert validate_exposition(text) == []


def test_default_buckets_strictly_increase():
    assert list(DEFAULT_BUCKETS) == sorted(set(DEFAULT_BUCKETS))


# ---- live /metrics conformance ---------------------------------------------


def _config():
    return {
        "settings": {"timeout": 60},
        "primary_backends": [
            # prefix_store=host so the quorum_tpu_prefix_store_* families
            # (and the engine-block store gauges/counters) are live on the
            # exposition this test validates.
            # decode_loop=2 so the megachunk knob rides the config path
            # the exposition's engine block reports.
            {"name": "LLM1",
             # kv_pages=1 so the paged-pool gauge/counter families
             # (ISSUE 17) ride the same live exposition; qos=1 so the
             # scheduler families (ISSUE 18) do too.
             "url": "tpu://llama-tiny?seed=3&slots=2&prefix_store=host"
                    "&decode_loop=2&kv_pages=1&qos=1",
             "model": "t"},
        ],
    }


async def test_live_metrics_exposition_validates():
    """The FULL /metrics output — engine gauges/counters plus every
    histogram family — passes the validator after real traffic, with one
    TYPE line per family and consistent histogram series."""
    async with make_client(_config()) as client:
        resp = await client.post(
            "/chat/completions",
            json={"model": "t", "max_tokens": 5,
                  "messages": [{"role": "user", "content": "hi"}]},
            headers={"Authorization": "Bearer x"},
        )
        assert resp.status_code == 200
        stream = await client.post(
            "/chat/completions",
            json={"model": "t", "max_tokens": 5, "stream": True,
                  "messages": [{"role": "user", "content": "hi"}]},
            headers={"Authorization": "Bearer x"},
        )
        assert stream.status_code == 200
        text = (await client.get("/metrics")).text

    assert validate_exposition(text) == [], validate_exposition(text)

    # exactly one TYPE line per family across the whole exposition
    type_lines = [ln for ln in text.splitlines() if ln.startswith("# TYPE ")]
    families = [ln.split()[2] for ln in type_lines]
    assert len(families) == len(set(families)), families

    # the acceptance histogram families, each with samples after traffic
    for fam in ("quorum_tpu_ttft_seconds",
                "quorum_tpu_inter_token_seconds",
                "quorum_tpu_queue_wait_seconds",
                "quorum_tpu_prefill_seconds",
                "quorum_tpu_decode_chunk_seconds"):
        assert f"# TYPE {fam} histogram" in text, fam
        assert f'{fam}_bucket{{le="+Inf"}}' in text, fam
        assert f"{fam}_sum" in text and f"{fam}_count" in text, fam

    # paged-KV pool observability (ISSUE 17): occupancy gauges + the
    # alias/COW counters, and the engine block's paged config/pool keys
    # mapped as gauges (a counter-typed pool level could never go down)
    for fam, typ in (("quorum_tpu_kv_pages_allocated", "gauge"),
                     ("quorum_tpu_kv_pages_free", "gauge"),
                     ("quorum_tpu_kv_page_alias_hits_total", "counter"),
                     ("quorum_tpu_kv_page_cow_copies_total", "counter"),
                     ("quorum_tpu_engine_kv_pages", "gauge"),
                     ("quorum_tpu_engine_kv_pages_free", "gauge")):
        assert f"# TYPE {fam} {typ}" in text, fam
    assert 'quorum_tpu_engine_kv_pages{backend="LLM1"} 1' in text
    # request duration is labeled by status class (2xx here)
    assert "# TYPE quorum_tpu_request_duration_seconds histogram" in text
    assert ('quorum_tpu_request_duration_seconds_bucket'
            '{status="2xx",le="+Inf"}') in text
    assert 'quorum_tpu_request_duration_seconds_count{status="2xx"}' in text

    # the tiered-prefix-store families (ISSUE 3): the restore histogram
    # exposes its full _bucket/_sum/_count triplet even before any hit,
    # and the counter/gauge families carry the counter/gauge TYPEs
    fam = "quorum_tpu_prefix_store_restore_seconds"
    assert f"# TYPE {fam} histogram" in text
    assert f'{fam}_bucket{{le="+Inf"}}' in text
    assert f"{fam}_sum" in text and f"{fam}_count" in text
    for counter in ("quorum_tpu_prefix_store_hits_total",
                    "quorum_tpu_prefix_store_restored_tokens_total",
                    "quorum_tpu_prefix_store_evictions_total"):
        assert f"# TYPE {counter} counter" in text, counter
    assert "# TYPE quorum_tpu_prefix_store_bytes gauge" in text
    # per-engine split: the store keys ride the engine block with the
    # right kinds (bytes/entries are gauges, the rest counters)
    assert ("# TYPE quorum_tpu_engine_prefix_store_bytes gauge") in text
    assert ("# TYPE quorum_tpu_engine_prefix_store_hits_total counter"
            ) in text

    # constrained-decoding families (ISSUE 5, docs/structured_output.md):
    # the compile histogram exposes its full triplet even before any
    # constrained traffic, the counters carry counter TYPEs, and the
    # per-engine split rides the engine block
    fam = "quorum_tpu_constrain_compile_seconds"
    assert f"# TYPE {fam} histogram" in text
    assert f'{fam}_bucket{{le="+Inf"}}' in text
    assert f"{fam}_sum" in text and f"{fam}_count" in text
    for counter in ("quorum_tpu_constrained_requests_total",
                    "quorum_tpu_constrain_masked_tokens_total",
                    "quorum_tpu_constrain_cache_hits_total",
                    "quorum_tpu_constrain_cache_misses_total"):
        assert f"# TYPE {counter} counter" in text, counter
    assert ("# TYPE quorum_tpu_engine_constrained_requests_total counter"
            in text)
    assert ("# TYPE quorum_tpu_engine_constrain_masked_tokens_total "
            "counter" in text)

    # speculative-decoding families (ISSUE 10, docs/tpu_backends.md): the
    # turn/draft/accepted counters and the per-turn acceptance histogram
    # expose even at zero (spec may not engage for this traffic), and the
    # engine block carries the per-engine split incl. the ring-resident
    # overlap counter
    for counter in ("quorum_tpu_spec_turns_total",
                    "quorum_tpu_spec_draft_tokens_total",
                    "quorum_tpu_spec_accepted_tokens_total"):
        assert f"# TYPE {counter} counter" in text, counter
    fam = "quorum_tpu_spec_accepted_per_turn"
    assert f"# TYPE {fam} histogram" in text
    assert f'{fam}_bucket{{le="+Inf"}}' in text
    assert f"{fam}_sum" in text and f"{fam}_count" in text
    for counter in ("quorum_tpu_engine_spec_turns_total",
                    "quorum_tpu_engine_spec_accepted_total",
                    "quorum_tpu_engine_spec_draft_tokens_total",
                    "quorum_tpu_engine_spec_overlapped_total"):
        assert f"# TYPE {counter} counter" in text, counter

    # QoS scheduler families (ISSUE 18, docs/scheduling.md): the
    # preemption counters and the per-class queue-depth gauge expose even
    # at zero (no preemption happened for this traffic), and the engine
    # block carries the qos flag plus the per-engine preempt/replay/shed
    # split — qos is a gauge (a flag), the rest counters
    for counter in ("quorum_tpu_preemptions_total",
                    "quorum_tpu_preempted_tokens_total"):
        assert f"# TYPE {counter} counter" in text, counter
    assert "# TYPE quorum_tpu_sched_queue_depth gauge" in text
    assert "# TYPE quorum_tpu_engine_qos gauge" in text
    assert 'quorum_tpu_engine_qos{backend="LLM1"} 1' in text
    for counter in ("quorum_tpu_engine_preemptions_total",
                    "quorum_tpu_engine_preempted_tokens_total",
                    "quorum_tpu_engine_replayed_tokens_total",
                    "quorum_tpu_engine_predictive_sheds_total"):
        assert f"# TYPE {counter} counter" in text, counter

    # drain lifecycle (ISSUE 19, docs/robustness.md "Zero-loss streams"):
    # the draining flag is a gauge (0 on a serving engine), the parked-
    # stream tally a counter — both expose even when no drain ever ran
    assert "# TYPE quorum_tpu_engine_draining gauge" in text
    assert 'quorum_tpu_engine_draining{backend="LLM1"} 0' in text
    assert ("# TYPE quorum_tpu_engine_drain_parked_total counter"
            in text)

    # recompile sentinel (ISSUE 9, docs/static_analysis.md): the counter
    # fed by the analysis/compile_watch.py log-compiles hook exposes a
    # sample even at zero — post-warmup compiles are a serving bug an
    # operator must be able to alert on
    assert "# TYPE quorum_tpu_recompiles_total counter" in text
    assert "quorum_tpu_recompiles_total " in text

    # megachunk-decode families (ISSUE 6): chunk segments per dispatch as
    # a histogram (samples after any decode traffic — unfused dispatches
    # observe 1), the configured decode_loop as an engine gauge, and the
    # executed-segment/drain-gap accounting as engine counters
    fam = "quorum_tpu_decode_loop_chunks"
    assert f"# TYPE {fam} histogram" in text
    assert f'{fam}_bucket{{le="+Inf"}}' in text
    assert f"{fam}_sum" in text and f"{fam}_count" in text
    assert "# TYPE quorum_tpu_engine_decode_loop gauge" in text
    assert ("# TYPE quorum_tpu_engine_decode_loop_chunks_total counter"
            in text)
    assert ("# TYPE quorum_tpu_engine_drain_gap_seconds_total counter"
            in text)
    assert 'quorum_tpu_engine_decode_loop{backend="LLM1"} 2' in text

    # disaggregated-serving families (ISSUE 8, docs/tpu_backends.md): the
    # KV-handoff histogram exposes its full triplet even on a colocated
    # engine (no handoff traffic), the byte counter carries a counter
    # TYPE, the per-group occupancy gauges are registered, and the
    # per-engine split (handoff totals + group sizes/occupancy) rides the
    # engine block with the right kinds
    fam = "quorum_tpu_kv_handoff_seconds"
    assert f"# TYPE {fam} histogram" in text
    # route= label (ISSUE 14): a process whose engines moved KV exposes
    # per-route series (direct/reshard/host-bounce/resident); a cold
    # family exposes the bare triplet — either way one +Inf bucket per
    # series, under the one TYPE line the validator already enforced
    import re

    assert re.search(
        fam + r'_bucket\{(?:route="[a-z-]+",)?le="\+Inf"\}', text)
    assert f"{fam}_sum" in text and f"{fam}_count" in text
    assert "# TYPE quorum_tpu_kv_handoff_bytes_total counter" in text
    assert "# TYPE quorum_tpu_prefill_group_active gauge" in text
    assert "# TYPE quorum_tpu_decode_group_active gauge" in text
    # per-stage decode occupancy (pipeline-staged decode, ISSUE 14): the
    # gauge family is registered with its bare sample on unstaged engines
    assert "# TYPE quorum_tpu_decode_stage_occupancy gauge" in text
    assert "# TYPE quorum_tpu_engine_disagg gauge" in text
    assert "# TYPE quorum_tpu_engine_prefill_group_devices gauge" in text
    assert "# TYPE quorum_tpu_engine_decode_group_devices gauge" in text
    assert "# TYPE quorum_tpu_engine_prefill_group_active gauge" in text
    assert "# TYPE quorum_tpu_engine_decode_group_active gauge" in text
    assert "# TYPE quorum_tpu_engine_kv_handoffs_total counter" in text
    assert "# TYPE quorum_tpu_engine_kv_handoff_bytes_total counter" in text
    assert ("# TYPE quorum_tpu_engine_kv_handoff_seconds_total counter"
            in text)
    # colocated engine: the knob gauge reads 0 (the disagg leg's nonzero
    # bytes are pinned by tests/test_disagg.py against a live handoff)
    assert 'quorum_tpu_engine_disagg{backend="LLM1"} 0' in text

    # zero-drain continuous batching (ISSUE 11, docs/tpu_backends.md):
    # the injection-overlap counter and the admission-stall counter expose
    # even at zero (this app serves a drain-based engine — overlap is
    # structurally 0 there and the stall only accumulates when a burst
    # actually clamps the ring), and the engine block carries the
    # per-engine split plus the knob gauge
    assert "# TYPE quorum_tpu_admission_overlap_total counter" in text
    assert ("# TYPE quorum_tpu_admission_stall_seconds_total counter"
            in text)
    assert "# TYPE quorum_tpu_engine_zero_drain gauge" in text
    assert ("# TYPE quorum_tpu_engine_admission_overlap_total counter"
            in text)
    assert ("# TYPE quorum_tpu_engine_admission_stall_seconds_total "
            "counter" in text)
    assert 'quorum_tpu_engine_zero_drain{backend="LLM1"} 0' in text
    assert 'quorum_tpu_engine_admission_overlap_total{backend="LLM1"} 0' \
        in text

    # telemetry families (ISSUE 12, docs/observability.md): the
    # per-program-family device-time histogram carries real samples after
    # any traffic (every dispatch attributes), labeled by family; the SLO
    # counters expose (the chat requests above were classified and scored
    # at teardown); the flight-recorder depth gauge and drop counter
    # expose; and the profiler-skip counter exposes even at zero
    fam = "quorum_tpu_dispatch_device_seconds"
    assert f"# TYPE {fam} histogram" in text
    assert f'{fam}_bucket{{family="' in text
    assert f"{fam}_sum" in text and f"{fam}_count" in text
    for counter in ("quorum_tpu_slo_good_total",
                    "quorum_tpu_slo_breached_total"):
        assert f"# TYPE {counter} counter" in text, counter
    # the served requests above carried a class and scored the deadline
    # stage (status 200 => good)
    assert 'quorum_tpu_slo_good_total{class="' in text
    assert "# TYPE quorum_tpu_flight_recorder_events gauge" in text
    assert ("# TYPE quorum_tpu_flight_recorder_dropped_total counter"
            in text)
    assert "# TYPE quorum_tpu_profile_skipped_total counter" in text
    assert "quorum_tpu_profile_skipped_total " in text

    # robustness families (docs/robustness.md): deadline sheds by stage,
    # HTTP retry attempts, and the per-engine rebuild/breaker block
    assert "# TYPE quorum_tpu_deadline_exceeded_total counter" in text
    assert "# TYPE quorum_tpu_backend_retries_total counter" in text
    assert "# TYPE quorum_tpu_engine_rebuilds_total counter" in text
    assert ("# TYPE quorum_tpu_engine_deadline_exceeded_total counter"
            in text)
    assert "# TYPE quorum_tpu_engine_breaker_state gauge" in text

    # router-tier families (ISSUE 13, quorum_tpu/router/ — registered
    # process-wide so `make metrics-check` covers them; on a serving
    # replica they expose at zero, on the router process they carry the
    # placement/failover/migration accounting)
    for counter in ("quorum_tpu_router_requests_total",
                    "quorum_tpu_router_affinity_hits_total",
                    "quorum_tpu_router_affinity_misses_total",
                    "quorum_tpu_router_failovers_total",
                    "quorum_tpu_router_migrated_bytes_total",
                    "quorum_tpu_router_migrated_chains_total",
                    "quorum_tpu_router_burn_demotions_total",
                    "quorum_tpu_router_stream_resumes_total",
                    "quorum_tpu_trace_propagated_total"):
        assert f"# TYPE {counter} counter" in text, counter

    # native quorum serving families (docs/quorum.md): shared-prefix
    # dedup savings, member-kill degradation + request outcomes, and the
    # aggregation hop's fallback visibility — process-wide counters, so
    # they expose (at zero here) on every tier
    for counter in ("quorum_tpu_quorum_dedup_tokens_total",
                    "quorum_tpu_quorum_degraded_total",
                    "quorum_tpu_quorum_requests_total",
                    "quorum_tpu_aggregate_degraded_total"):
        assert f"# TYPE {counter} counter" in text, counter

    # fleet-plane families (ISSUE 16): burn gauge absorbed from replica
    # telemetry and the telemetry-poll latency histogram
    assert "# TYPE quorum_tpu_router_replica_burn gauge" in text
    assert ("# TYPE quorum_tpu_telemetry_poll_seconds histogram"
            in text)

    # _count == +Inf bucket and bucket monotonicity for one family, by hand
    # (belt to the validator's braces)
    inf = count = None
    prev = -1
    for ln in text.splitlines():
        if ln.startswith("quorum_tpu_queue_wait_seconds_bucket"):
            v = int(float(ln.rsplit(" ", 1)[1]))
            assert v >= prev
            prev = v
            if 'le="+Inf"' in ln:
                inf = v
        elif ln.startswith("quorum_tpu_queue_wait_seconds_count"):
            count = int(float(ln.rsplit(" ", 1)[1]))
    assert inf is not None and count is not None and inf == count
    assert count >= 1  # the requests above really were observed
