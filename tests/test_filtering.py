"""Unit tests for the incremental thinking-tag filter.

Mirrors the reference's coverage (/root/reference/tests/test_thinking_tag_filter.py):
basic/multiple blocks, tags split across feeds, nesting, unclosed/mismatched
tags, case-insensitivity, flush semantics, streaming simulation, multi-tag,
newlines — plus batch strip_thinking_tags behavior.
"""

import pytest

from quorum_tpu.filtering import ThinkingTagFilter, strip_thinking_tags


TAGS = ["think", "reason"]


def run_feed(chunks, tags=TAGS):
    f = ThinkingTagFilter(tags)
    out = "".join(f.feed(c) for c in chunks)
    return out + f.flush()


class TestThinkingTagFilter:
    def test_basic_block_removed(self):
        assert run_feed(["Hello <think>secret</think> world"]) == "Hello  world"

    def test_multiple_blocks(self):
        assert (
            run_feed(["a<think>x</think>b<think>y</think>c"]) == "abc"
        )

    def test_tag_split_across_feeds(self):
        assert run_feed(["Hello <thi", "nk>hidden</th", "ink> world"]) == "Hello  world"

    def test_close_tag_split_across_feeds(self):
        assert run_feed(["<think>hidden</", "think>visible"]) == "visible"

    def test_nested_tags(self):
        assert (
            run_feed(["out<think>a<think>b</think>c</think>side"]) == "outside"
        )

    def test_nested_different_tags(self):
        assert run_feed(["x<think>a<reason>b</reason>c</think>y"]) == "xy"

    def test_unclosed_tag_discarded_at_flush(self):
        assert run_feed(["visible<think>never closed"]) == "visible"

    def test_close_without_open_passes_through(self):
        assert run_feed(["no block</think>here"]) == "no block</think>here"

    def test_case_insensitive(self):
        assert run_feed(["a<THINK>hidden</ThInK>b"]) == "ab"

    def test_unknown_tag_untouched(self):
        assert run_feed(["a<other>keep</other>b"]) == "a<other>keep</other>b"

    def test_partial_tag_that_is_not_a_tag_emitted(self):
        # "<thx" can never become "<think>" — must be emitted, not held.
        assert run_feed(["a<thx", "yz"]) == "a<thxyz"

    def test_lone_angle_bracket(self):
        assert run_feed(["1 < 2 and 3 > 2"]) == "1 < 2 and 3 > 2"

    def test_flush_discards_partial_open_tag(self):
        f = ThinkingTagFilter(TAGS)
        assert f.feed("abc<thi") == "abc"
        assert f.flush() == ""

    def test_flush_emits_plain_buffer(self):
        f = ThinkingTagFilter(TAGS)
        f.feed("hello")
        assert f.flush() == ""  # "hello" already emitted by feed

    def test_streaming_token_by_token(self):
        text = "Start <think>internal reasoning here</think>End"
        chunks = [text[i : i + 3] for i in range(0, len(text), 3)]
        assert run_feed(chunks) == "Start End"

    def test_content_with_newlines(self):
        assert (
            run_feed(["line1\n<think>\nhidden\nlines\n</think>\nline2"])
            == "line1\n\nline2"
        )

    def test_reuse_after_flush(self):
        f = ThinkingTagFilter(TAGS)
        f.feed("<think>a")
        f.flush()
        assert f.feed("clean") == "clean"
        assert f.flush() == ""

    def test_empty_feed(self):
        f = ThinkingTagFilter(TAGS)
        assert f.feed("") == ""
        assert f.flush() == ""


class TestStripThinkingTags:
    def test_basic(self):
        assert strip_thinking_tags("a <think>x</think> b", ["think"]) == "a  b".strip()

    def test_multiline(self):
        assert (
            strip_thinking_tags("keep\n<think>\nmulti\nline\n</think>\nend", ["think"])
            == "keep\n\nend"
        )

    def test_hide_false_noop(self):
        s = "a <think>x</think> b"
        assert strip_thinking_tags(s, ["think"], hide=False) == s

    def test_case_insensitive(self):
        assert strip_thinking_tags("a<THINK>x</think>b", ["think"]) == "ab"

    def test_multiple_tags(self):
        assert (
            strip_thinking_tags("a<think>x</think>b<reason>y</reason>c", ["think", "reason"])
            == "abc"
        )

    def test_unclosed_left_alone(self):
        # Batch strip only removes complete blocks (regex parity).
        s = "a<think>unclosed"
        assert strip_thinking_tags(s, ["think"]) == s
