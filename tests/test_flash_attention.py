"""Flash-attention kernel vs the XLA-native reference, in interpreter mode.

The reference path (quorum_tpu.ops.attention.prefill_attention) is itself
validated end-to-end against transformers' forward in tests/test_hf_loader.py,
so matching it here transitively validates the kernel.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quorum_tpu.ops.attention import prefill_attention
from quorum_tpu.ops.flash_attention import (
    flash_prefill_attention,
    flash_supported,
)

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def rand(key, shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


def run_both(b, h, n_kv, s, hd, lengths, block_q=128, block_k=128):
    q = rand(0, (b, h, s, hd))
    k = rand(1, (b, n_kv, s, hd))
    v = rand(2, (b, n_kv, s, hd))
    lengths = jnp.asarray(lengths, jnp.int32)
    ref = prefill_attention(q, k, v, lengths)
    out = flash_prefill_attention(
        q, k, v, lengths, block_q=block_q, block_k=block_k, interpret=True
    )
    return np.asarray(out), np.asarray(ref), lengths


def assert_valid_rows_close(out, ref, lengths, atol=2e-5):
    """Compare only rows inside each batch row's valid length — padded query
    rows are unspecified (never read downstream)."""
    for bi, n in enumerate(np.asarray(lengths)):
        np.testing.assert_allclose(
            out[bi, :, :n, :], ref[bi, :, :n, :], atol=atol, rtol=1e-4
        )


def test_flash_matches_reference_single_block():
    out, ref, lengths = run_both(1, 2, 2, 128, 64, [128])
    assert_valid_rows_close(out, ref, lengths)


def test_flash_matches_reference_multi_block_causal():
    out, ref, lengths = run_both(1, 2, 2, 256, 64, [256])
    assert_valid_rows_close(out, ref, lengths)


def test_flash_gqa_head_mapping():
    out, ref, lengths = run_both(1, 4, 2, 128, 64, [128])
    assert_valid_rows_close(out, ref, lengths)


def test_flash_length_masking_batched():
    out, ref, lengths = run_both(2, 2, 2, 128, 64, [37, 101])
    assert_valid_rows_close(out, ref, lengths)
    assert not np.isnan(out).any()  # padded rows defined (no NaN)


def test_flash_small_bucket_uses_clamped_blocks():
    # bucket 64 < default 128: tiles clamp to the sequence
    out, ref, lengths = run_both(1, 2, 2, 64, 64, [50])
    assert_valid_rows_close(out, ref, lengths)


def test_flash_supported_gates():
    assert flash_supported((1, 4, 256, 64), (1, 2, 256, 64), 128, 128)
    assert not flash_supported((1, 4, 100, 64), (1, 2, 100, 64), 128, 128)
    assert not flash_supported((1, 3, 256, 64), (1, 2, 256, 64), 128, 128)


def test_prefill_uses_fallback_off_tpu():
    """On CPU (tests force JAX_PLATFORMS=cpu) the dispatcher must take the
    XLA reference path, not the kernel."""
    from quorum_tpu.ops.flash_attention import flash_enabled

    assert jax.default_backend() == "cpu"
    assert not flash_enabled()
