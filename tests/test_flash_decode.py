"""Pallas decode-attention kernel vs the masked-dense reference.

The kernel (ops/flash_decode.py) must match ops.attention.decode_attention —
the engine's numerical ground truth — for every layout the engine produces:
GQA and MHA head counts, skewed per-row lengths (the kernel's reason to
exist: per-row-exact cache reads), single-tile and multi-tile histories,
bf16 and f32. Interpret mode on CPU, same strategy as test_flash_attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quorum_tpu.ops.attention import decode_attention
from quorum_tpu.ops.flash_decode import (
    DEFAULT_BLOCK_K,
    flash_decode_attention,
    flash_decode_supported,
)

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def _mk(b, h, n_kv, t, hd, dtype, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, h, 1, hd), dtype)
    k = jax.random.normal(ks[1], (b, n_kv, t, hd), dtype)
    v = jax.random.normal(ks[2], (b, n_kv, t, hd), dtype)
    return q, k, v


@pytest.mark.parametrize("h,n_kv", [(8, 2), (4, 4), (12, 3)])
@pytest.mark.parametrize("t,block_k", [(256, 128), (512, 128), (128, 128)])
def test_matches_reference_skewed_lengths(h, n_kv, t, block_k):
    q, k, v = _mk(4, h, n_kv, t, 64, jnp.float32)
    # Heavily skewed: one row near-empty, one full — the kernel's win case.
    lengths = jnp.array([1, t // 2 - 3, t, 7], jnp.int32)
    ref = decode_attention(q, k, v, lengths)
    got = flash_decode_attention(q, k, v, lengths,
                                 block_k=block_k, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_matches_reference_bf16():
    q, k, v = _mk(2, 8, 4, 256, 128, jnp.bfloat16, seed=3)
    lengths = jnp.array([255, 64], jnp.int32)
    ref = decode_attention(q, k, v, lengths)
    got = flash_decode_attention(q, k, v, lengths,
                                 block_k=128, interpret=True)
    assert got.dtype == ref.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)


def test_scalar_length_broadcasts():
    q, k, v = _mk(3, 4, 2, 128, 64, jnp.float32, seed=5)
    ref = decode_attention(q, k, v, 97)
    got = flash_decode_attention(q, k, v, 97, block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_unsupported_shapes_fall_back():
    # t not divisible by the tile → reference path (still correct).
    q, k, v = _mk(2, 4, 2, 96, 64, jnp.float32, seed=7)
    lengths = jnp.array([5, 96], jnp.int32)
    got = flash_decode_attention(q, k, v, lengths,
                                 block_k=DEFAULT_BLOCK_K, interpret=True)
    ref = decode_attention(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert not flash_decode_supported(q.shape, k.shape, 64)  # 96 % 64 != 0


def test_under_vmap_members_axis():
    # The stacked-members engine vmaps decode over the leading weight-set
    # axis; the kernel must compose with vmap (Pallas lifts it to a grid
    # dimension).
    m, b, h, n_kv, t, hd = 3, 2, 8, 4, 256, 64
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(ks[0], (m, b, h, 1, hd), jnp.float32)
    k = jax.random.normal(ks[1], (m, b, n_kv, t, hd), jnp.float32)
    v = jax.random.normal(ks[2], (m, b, n_kv, t, hd), jnp.float32)
    lengths = jnp.array([19, 250], jnp.int32)
    ref = jax.vmap(lambda qq, kk, vv: decode_attention(qq, kk, vv, lengths))(
        q, k, v)
    got = jax.vmap(lambda qq, kk, vv: flash_decode_attention(
        qq, kk, vv, lengths, block_k=128, interpret=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_engine_serves_identically_with_kernel(monkeypatch):
    # End-to-end through the continuous-batching engine: the kernel path
    # (interpret mode) must reproduce the default masked-dense path
    # token-for-token, co-batching skewed-length requests.
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = resolve_spec("llama-tiny", {"n_kv_heads": "4", "max_seq": "256"})
    sampler = SamplerConfig(temperature=0.8, top_p=0.9)
    long_prompt = list(range(3, 120))

    def serve():
        eng = InferenceEngine(spec, decode_chunk=4, n_slots=2)
        out = [
            eng.generate(p, max_new_tokens=8, sampler=sampler, seed=5).token_ids
            for p in ([3, 4, 5], long_prompt)
        ]
        eng.shutdown()
        return out

    monkeypatch.delenv("QUORUM_TPU_FLASH_DECODE", raising=False)
    ref = serve()
    monkeypatch.setenv("QUORUM_TPU_FLASH_DECODE", "interpret")
    got = serve()
    assert got == ref


def test_flash_decode_url_knob(monkeypatch):
    """The per-backend flash_decode= knob (first-class since ISSUE 6):
    resolves per engine without the env var, is validated at config time,
    and serves token-identically to the masked-dense path; the env var
    stays a process override that beats the knob."""
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.ops.flash_decode import (
        parse_flash_decode,
        resolve_flash_decode,
    )
    from quorum_tpu.ops.sampling import SamplerConfig

    monkeypatch.delenv("QUORUM_TPU_FLASH_DECODE", raising=False)
    assert parse_flash_decode("1") == "1"
    assert parse_flash_decode("off") == "0"
    assert parse_flash_decode("interpret") == "interpret"
    with pytest.raises(ValueError):
        parse_flash_decode("maybe")
    # knob drives resolution when the env var is unset...
    assert resolve_flash_decode("interpret") == "interpret"
    assert resolve_flash_decode(None) == ""
    # ...and the env override wins over the knob (A/B scripts flip it)
    monkeypatch.setenv("QUORUM_TPU_FLASH_DECODE", "0")
    assert resolve_flash_decode("interpret") == ""
    monkeypatch.setenv("QUORUM_TPU_FLASH_DECODE", "interpret")
    assert resolve_flash_decode("0") == "interpret"
    # env takes the URL knob's spellings ("off" parses, wins over the knob)
    monkeypatch.setenv("QUORUM_TPU_FLASH_DECODE", "off")
    assert resolve_flash_decode("interpret") == ""
    # unparseable env is a LOUD off (logged), never a crash — one typo'd
    # var must not brick every engine construction in the process
    monkeypatch.setenv("QUORUM_TPU_FLASH_DECODE", "garbage")
    assert resolve_flash_decode("interpret") == ""
    monkeypatch.delenv("QUORUM_TPU_FLASH_DECODE", raising=False)

    spec = resolve_spec("llama-tiny", {"n_kv_heads": "4", "max_seq": "256"})
    sampler = SamplerConfig(temperature=0.8, top_p=0.9)

    def serve(flash):
        eng = InferenceEngine(spec, decode_chunk=4, n_slots=2,
                              flash_decode=flash)
        assert eng._flash == ("interpret" if flash == "interpret" else "")
        out = eng.generate([3, 4, 5], max_new_tokens=8, sampler=sampler,
                           seed=5).token_ids
        eng.shutdown()
        return out

    assert serve(None) == serve("interpret")
