"""The fleet observability plane (docs/observability.md "Fleet plane").

Cross-tier trace propagation (one W3C trace-id from the router through a
replica's server into engine events, surviving failover), per-replica
telemetry export absorbed into the router's staleness-bounded
TelemetryView, burn-aware placement demotion, and the merged fleet
timeline with cross-process clock alignment. Everything here runs over
jax-free fake replicas on real sockets — the real-engine leg lives in
scripts/router_bench.py and the chaos harness.
"""

import asyncio
import threading
import time

import httpx
import pytest

from quorum_tpu.router import affinity
from quorum_tpu.router.app import RouterConfig, create_router_app
from quorum_tpu.router.fake_replica import (
    FakeReplicaState,
    create_fake_replica_app,
)
from quorum_tpu.router.ring import BoundedLoadRing, hash_key
from quorum_tpu.router.telemetry_view import TelemetryView
from quorum_tpu.telemetry import tracecontext
from quorum_tpu.telemetry.recorder import RECORDER, merged_trace_events


# ---- trace-context primitives -----------------------------------------------


def test_traceparent_round_trip():
    tid, sid = tracecontext.new_trace_id(), tracecontext.new_span_id()
    assert len(tid) == 32 and len(sid) == 16
    header = tracecontext.format_traceparent(tid, sid)
    assert tracecontext.parse_traceparent(header) == (tid, sid)
    child_sid, child_header = tracecontext.child_traceparent(tid)
    assert child_sid != sid
    assert tracecontext.parse_traceparent(child_header) == (tid, child_sid)


def test_traceparent_rejects_malformed():
    tid, sid = "ab" * 16, "cd" * 8
    good = f"00-{tid}-{sid}-01"
    assert tracecontext.parse_traceparent(good) == (tid, sid)
    assert tracecontext.parse_traceparent(good.upper()) == (tid, sid)
    for bad in (None, "", 42, "junk", f"01-{tid}-{sid}-01",
                f"00-{tid}-{sid}", f"00-{tid[:-1]}-{sid}-01",
                f"00-{'0' * 32}-{sid}-01",       # zero trace-id
                f"00-{tid}-{'0' * 16}-01",       # zero span-id
                f"00-{tid}-{sid}-zz-extra"):
        assert tracecontext.parse_traceparent(bad) is None, bad


def test_engine_direct_requests_self_mint_a_trace_id():
    """A _Request built outside any traced context (engine.generate from
    a script) mints its own 32-hex rid — engine timelines stay
    correlatable even without a server above them — while one built
    inside a traced context inherits the trace-id."""
    from quorum_tpu.engine.engine import _Request
    from quorum_tpu.observability import (
        TRACE_PROPAGATED,
        RequestTrace,
        use_trace,
    )
    from quorum_tpu.ops.sampling import SamplerConfig

    def mk():
        return _Request([1, 2, 3], 4, SamplerConfig(), 0, None,
                        threading.Event(), 4)

    before = TRACE_PROPAGATED.value_of(source="engine")
    req = mk()
    assert len(req.rid) == 32 and int(req.rid, 16) != 0
    assert TRACE_PROPAGATED.value_of(source="engine") == before + 1
    tid = tracecontext.new_trace_id()
    with use_trace(RequestTrace("req-x", trace_id=tid, span_id="a" * 16)):
        assert mk().rid == tid
    # a trace WITHOUT a trace-id (legacy caller) falls back to its id
    with use_trace(RequestTrace("req-y")):
        assert mk().rid == "req-y"


# ---- telemetry view ---------------------------------------------------------


def _snapshot(clock: float, burn: dict[str, float] | None = None) -> dict:
    return {"clock": clock,
            "slo": {cls: {"burn_rate": rate, "stages": {}}
                    for cls, rate in (burn or {}).items()}}


def test_telemetry_view_offset_estimation():
    view = TelemetryView(max_age_s=10.0)
    t0 = time.perf_counter()
    t1 = t0 + 0.010
    # replica clock runs 5 s ahead: offset ≈ midpoint − (midpoint + 5)
    view.absorb("r0", _snapshot((t0 + t1) / 2 + 5.0), t0, t1)
    assert view.fresh("r0")
    assert view.offset("r0") == pytest.approx(-5.0, abs=1e-6)
    # shapeless clock → no offset, snapshot still served
    view.absorb("r1", {"slo": {}}, t0, t1)
    assert view.offset("r1") is None and view.get("r1") is not None


def test_telemetry_view_staleness_and_fail_open():
    view = TelemetryView(max_age_s=0.05)
    view.absorb("r0", _snapshot(time.perf_counter(),
                                {"interactive": 0.9}), 0.0, 0.0)
    assert view.burn_rate("r0", "interactive") == pytest.approx(0.9)
    time.sleep(0.08)
    # stale: EVERYTHING answers None/empty — the fail-open contract
    assert not view.fresh("r0")
    assert view.get("r0") is None
    assert view.burn_rate("r0", "interactive") is None
    assert view.burn_rates("r0") == {}
    assert view.offset("r0") is None
    snap = view.snapshot()
    assert snap["r0"]["fresh"] is False
    # never-seen replica: None, not a KeyError
    assert view.burn_rate("ghost", "interactive") is None
    # malformed burn shapes: None, never a crash or a zero
    view.absorb("r0", {"clock": 1.0, "slo": {"interactive": "broken"}},
                0.0, 0.0)
    assert view.burn_rate("r0", "interactive") is None


# ---- burn demotion in the ring ----------------------------------------------


def test_ring_candidates_demoted_partition():
    ring = BoundedLoadRing()
    for n in ("a", "b", "c", "d"):
        ring.add(n)
    key = hash_key(b"burning conversation")
    base = ring.candidates(key)
    hot = base[0]
    out = ring.candidates(key, demoted={hot})
    # same membership, demoted member at the tail, others keep order
    assert sorted(out) == sorted(base)
    assert out[-1] == hot
    assert out[:-1] == [n for n in base if n != hot]
    # demotion composes with bounded load: overloaded AND burning sinks
    # below a merely-overloaded member
    loads = {n: (50 if n in base[:2] else 0) for n in base}
    combined = ring.candidates(key, loads, demoted={base[0]})
    assert combined[-1] == base[0] and combined[-2] == base[1]
    # empty/None demoted set: unchanged
    assert ring.candidates(key, demoted=set()) == base
    assert ring.candidates(key, demoted=None) == base


# ---- router cluster over fake replicas --------------------------------------


class _Cluster:
    """N fake replicas + the router app (real sockets, test event loop)."""

    def __init__(self, n: int = 2, *, ready_interval: float = 0.0,
                 state_kw: list[dict] | None = None, **cfg_kw):
        self.n = n
        self.ready_interval = ready_interval
        self.state_kw = state_kw or [{} for _ in range(n)]
        self.cfg_kw = cfg_kw
        self.states: list[FakeReplicaState] = []
        self.servers = []
        self.urls: list[str] = []

    async def __aenter__(self):
        from quorum_tpu.server.serve import start_server

        for i in range(self.n):
            st = FakeReplicaState(f"r{i}", **self.state_kw[i])
            srv = await start_server(
                create_fake_replica_app(st), "127.0.0.1", 0)
            self.states.append(st)
            self.servers.append(srv)
            self.urls.append(
                f"http://127.0.0.1:{srv.sockets[0].getsockname()[1]}")
        self.cfg = RouterConfig(
            replicas=[(f"r{i}", u) for i, u in enumerate(self.urls)],
            ready_interval=self.ready_interval, **self.cfg_kw)
        self.app = create_router_app(self.cfg)
        self.mgr = self.app.state["replica_set"]
        self.client = httpx.AsyncClient(
            transport=httpx.ASGITransport(app=self.app),
            base_url="http://router", timeout=30.0)
        return self

    async def __aexit__(self, *exc):
        await self.client.aclose()
        await self.mgr.aclose()
        for srv in self.servers:
            srv.close()

    async def chat(self, messages, headers=None, **kw):
        return await self.client.post(
            "/chat/completions",
            json={"model": "m", "messages": messages, **kw},
            headers=headers)


def _conv(i: int) -> list[dict]:
    return [{"role": "user", "content": f"fleet conversation {i}: "
             "what is the opening move?"}]


def _events_for(rid: str, events: list[dict]) -> list[dict]:
    return [ev for ev in events if ev.get("rid") == rid]


async def test_router_mints_and_propagates_trace_id():
    async with _Cluster(2) as c:
        r = await c.chat(_conv(0))
        assert r.status_code == 200
        parsed = tracecontext.parse_traceparent(r.headers["traceparent"])
        assert parsed is not None
        trace_id = parsed[0]
        # the trace-id IS the router's request id
        assert r.headers["x-request-id"] == trace_id
        served_by = r.headers["x-routed-to"]
        # router's recorder: the route event carries the trace-id
        routed = [ev for ev in _events_for(trace_id, RECORDER.snapshot())
                  if ev["kind"] == "router-route"]
        assert routed and routed[-1]["replica"] == served_by
        assert "failover" not in routed[-1]
        assert len(routed[-1]["span"]) == 16
        # replica's recorder: dispatch + reap joined on the SAME id
        state = c.states[int(served_by[1:])]
        kinds = {ev["kind"]
                 for ev in _events_for(trace_id, state.recorder.snapshot())}
        assert kinds == {"dispatch", "reap"}


async def test_router_honors_client_traceparent():
    async with _Cluster(2) as c:
        tid = tracecontext.new_trace_id()
        header = tracecontext.format_traceparent(tid, "ab" * 8)
        r = await c.chat(_conv(1), headers={"traceparent": header})
        got_tid, got_span = tracecontext.parse_traceparent(
            r.headers["traceparent"])
        assert got_tid == tid          # same trace
        assert got_span != "ab" * 8    # fresh hop span
        assert r.headers["x-request-id"] == tid
        # body knob works for header-less clients
        tid2 = tracecontext.new_trace_id()
        r = await c.chat(
            _conv(2),
            traceparent=tracecontext.format_traceparent(tid2, "cd" * 8))
        assert r.headers["x-request-id"] == tid2
        # a malformed header is ignored → minted, never trusted
        r = await c.chat(_conv(3), headers={"traceparent": "garbage"})
        minted = r.headers["x-request-id"]
        assert len(minted) == 32 and minted != tid


async def test_failover_keeps_trace_id_with_new_hop_span():
    async with _Cluster(2) as c:
        # a conversation whose affinity home is r0
        body = None
        for i in range(64):
            cand = {"messages": _conv(100 + i)}
            key = affinity.conversation_key(cand, c.cfg.affinity_chunk)
            if c.mgr.ring.primary(key) == "r0":
                body = cand["messages"]
                break
        assert body is not None
        # kill r0's listener: the attempt on it fails pre-stream
        c.servers[0].close()
        await c.servers[0].wait_closed()
        r = await c.chat(body)
        assert r.status_code == 200
        assert r.headers["x-routed-to"] == "r1"
        trace_id = r.headers["x-request-id"]
        events = _events_for(trace_id, RECORDER.snapshot())
        failed = [ev for ev in events if ev["kind"] == "router-failover"]
        routed = [ev for ev in events if ev["kind"] == "router-route"]
        assert failed and failed[0]["replica"] == "r0"
        assert routed and routed[0]["replica"] == "r1"
        # same trace-id end to end; the serving hop is marked failover
        # and rides a DIFFERENT span than the failed attempt
        assert routed[0]["failover"] == 1
        assert routed[0]["span"] != failed[0]["span"]
        # the survivor's recorder saw the same trace-id
        assert _events_for(trace_id, c.states[1].recorder.snapshot())


async def test_streaming_carries_traceparent():
    async with _Cluster(2) as c:
        async with c.client.stream(
            "POST", "/chat/completions",
            json={"model": "m", "stream": True, "messages": _conv(5)},
        ) as resp:
            assert resp.status_code == 200
            tid, _ = tracecontext.parse_traceparent(
                resp.headers["traceparent"])
            assert resp.headers["x-request-id"] == tid
            await resp.aread()
        served = resp.headers["x-routed-to"]
        state = c.states[int(served[1:])]
        kinds = {ev["kind"]
                 for ev in _events_for(tid, state.recorder.snapshot())}
        assert kinds == {"dispatch", "reap"}


# ---- telemetry poll + burn-aware placement ----------------------------------


async def test_poller_absorbs_telemetry_and_burn_demotes():
    from quorum_tpu.observability import (
        ROUTER_BURN_DEMOTIONS,
        ROUTER_REPLICA_BURN,
    )

    async with _Cluster(2, burn_threshold=0.5) as c:
        await c.mgr.poll_once()
        # telemetry absorbed for both; no burn scripted → nobody demoted
        assert c.mgr.telemetry.fresh("r0") and c.mgr.telemetry.fresh("r1")
        assert c.mgr.telemetry.offset("r0") is not None
        assert c.mgr.burn_demoted() == set()
        # script r0 burning its interactive budget, re-poll
        async with httpx.AsyncClient() as direct:
            resp = await direct.post(
                f"{c.urls[0]}/admin/burn?class=interactive&rate=0.9")
            assert resp.status_code == 200
        await c.mgr.poll_once()
        assert c.mgr.burn_demoted() == {"r0"}
        assert ROUTER_REPLICA_BURN.value_of(
            replica="r0", slo_class="interactive") == pytest.approx(0.9)
        # every placement now ranks r0 last; the demotion is counted
        before = ROUTER_BURN_DEMOTIONS.value_of(replica="r0")
        for i in range(12):
            key = affinity.conversation_key({"messages": _conv(200 + i)},
                                            c.cfg.affinity_chunk)
            _, candidates = c.mgr.placement(key)
            assert candidates[-1] == "r0"
        assert ROUTER_BURN_DEMOTIONS.value_of(replica="r0") == before + 12
        # membership untouched: r0 is still in the ring, still primary
        # for its key ranges
        assert "r0" in c.mgr.ring
        # requests route to the healthy sibling
        r = await c.chat(_conv(201))
        assert r.headers["x-routed-to"] == "r1"
        # burn below threshold → back to normal placement
        async with httpx.AsyncClient() as direct:
            await direct.post(
                f"{c.urls[0]}/admin/burn?class=interactive&rate=0.1")
        await c.mgr.poll_once()
        assert c.mgr.burn_demoted() == set()


async def test_burn_demotion_fails_open_on_stale_telemetry():
    async with _Cluster(2, burn_threshold=0.5,
                        telemetry_max_age=0.05) as c:
        async with httpx.AsyncClient() as direct:
            await direct.post(
                f"{c.urls[0]}/admin/burn?class=interactive&rate=0.9")
        await c.mgr.poll_once()
        assert c.mgr.burn_demoted() == {"r0"}
        # telemetry ages out → the demotion evaporates (fail-open), even
        # though the replica is still burning
        await asyncio.sleep(0.08)
        assert c.mgr.burn_demoted() == set()
        key = affinity.conversation_key({"messages": _conv(300)},
                                        c.cfg.affinity_chunk)
        _, candidates = c.mgr.placement(key)
        assert sorted(candidates) == ["r0", "r1"]
        # threshold <= 0 disables demotion outright
        c.mgr.burn_threshold = 0.0
        await c.mgr.poll_once()
        assert c.mgr.burn_demoted() == set()


# ---- fleet timeline ---------------------------------------------------------


async def test_fleet_timeline_aligns_skewed_clocks():
    """Two replicas with multi-second clock skews: after the router's
    offset correction, one request's router event and its serving
    replica's dispatch/reap land within a real-request's duration of
    each other — and every trace-id's replica events sit between no
    earlier than its route decision minus an RTT."""
    skews = [{"clock_skew": 5.0}, {"clock_skew": -3.0}]
    async with _Cluster(2, state_kw=skews) as c:
        await c.mgr.poll_once()
        for name, skew in (("r0", 5.0), ("r1", -3.0)):
            offset = c.mgr.telemetry.offset(name)
            assert offset == pytest.approx(-skew, abs=0.5), name
        rids = []
        for i in range(6):
            r = await c.chat(_conv(400 + i))
            rids.append(r.headers["x-request-id"])
        resp = await c.client.get("/debug/fleet/timeline")
        assert resp.status_code == 200
        body = resp.json()
        assert body["clock"] == "router perf_counter"
        by_name = {row["name"]: row for row in body["replicas"]}
        assert by_name["r0"]["clock_aligned"] is True
        events = body["events"]
        assert events == sorted(events, key=lambda e: e.get("t", 0.0))
        for rid in rids:
            mine = _events_for(rid, events)
            procs = {ev["process"] for ev in mine}
            assert "router" in procs and len(procs) == 2, rid
            # aligned: all of one request's events within a second,
            # despite ±5 s of raw skew
            stamps = [ev["t"] for ev in mine]
            assert max(stamps) - min(stamps) < 1.0, rid
            route = [ev for ev in mine if ev["kind"] == "router-route"]
            reap = [ev for ev in mine if ev["kind"] == "reap"]
            assert route and reap
            assert reap[0]["t_ready"] >= reap[0]["t_issue"]
        # perfetto export: one process per tier member, rid in args
        resp = await c.client.get("/debug/fleet/timeline?format=perfetto")
        trace = resp.json()
        names = {m["args"]["name"] for m in trace["traceEvents"]
                 if m.get("ph") == "M" and m["name"] == "process_name"}
        assert names == {"router", "r0", "r1"}
        assert any(ev.get("args", {}).get("rid") == rids[0]
                   for ev in trace["traceEvents"])
        bad = await c.client.get("/debug/fleet/timeline?format=nope")
        assert bad.status_code == 400


async def test_router_timeline_endpoint():
    async with _Cluster(2) as c:
        r = await c.chat(_conv(500))
        rid = r.headers["x-request-id"]
        resp = await c.client.get("/debug/router/timeline")
        body = resp.json()
        assert body["clock"] == "perf_counter"
        assert body["capacity"] >= 16
        assert _events_for(rid, body["events"])
        pf = (await c.client.get(
            "/debug/router/timeline?format=perfetto")).json()
        assert pf["displayTimeUnit"] == "ms"
        assert (await c.client.get(
            "/debug/router/timeline?format=bogus")).status_code == 400


def test_merged_trace_events_applies_offsets():
    groups = [
        ("router", [{"t": 10.0, "kind": "router-route", "rid": "t1",
                     "loop": "router"}], 0.0),
        ("r0", [{"t": 14.0, "kind": "reap", "rid": "t1", "engine": "r0",
                 "loop": "decode", "t_issue": 13.5, "t_ready": 14.0,
                 "family": "fake"}], -3.4),
    ]
    out = merged_trace_events(groups)
    slices = [ev for ev in out if ev.get("ph") == "X"]
    instants = [ev for ev in out if ev.get("ph") == "i"]
    assert len(slices) == 1 and len(instants) == 1
    # offsets land both events on one timebase (µs)
    assert instants[0]["ts"] == pytest.approx(10.0 * 1e6)
    assert slices[0]["ts"] == pytest.approx((13.5 - 3.4) * 1e6)
    assert slices[0]["dur"] == pytest.approx(0.5 * 1e6)
    assert slices[0]["args"]["rid"] == "t1"
    procs = {m["args"]["name"] for m in out
             if m.get("ph") == "M" and m["name"] == "process_name"}
    assert procs == {"router", "r0"}
    # malformed events are skipped, never a crash
    assert merged_trace_events(
        [("x", [{"t": "bad", "kind": "k"}, "junk"], 0.0)])


# ---- replica-tier surfaces --------------------------------------------------


async def test_fake_replica_telemetry_shape():
    async with _Cluster(1) as c:
        async with httpx.AsyncClient() as direct:
            body = (await direct.get(
                f"{c.urls[0]}/debug/telemetry")).json()
            assert isinstance(body["clock"], float)
            assert body["status"] == "healthy"
            assert body["slo"] == {} and body["queue_depth"] == 0
            assert "prefix_store_bytes" in body
            # bad burn knob → 400
            r = await direct.post(f"{c.urls[0]}/admin/burn?rate=lots")
            assert r.status_code == 400


def test_server_telemetry_and_traceparent(monkeypatch):
    """The real server tier: /debug/telemetry serves the snapshot shape
    and /chat/completions accepts + echoes traceparent (header and body
    knob), with the trace carrying the trace-id."""
    from quorum_tpu.backends.fake import FakeBackend
    from tests.conftest import make_client

    async def run():
        config = {"settings": {"timeout": 5},
                  "primary_backends": [
                      {"name": "F", "url": "http://f.example/v1",
                       "model": "f"}]}
        async with make_client(config,
                               F=FakeBackend("F", text="x")) as client:
            body = (await client.get("/debug/telemetry")).json()
            assert "clock" in body and "slo" in body
            assert body["status"] in ("healthy", "degraded", "unhealthy")
            tid = tracecontext.new_trace_id()
            header = tracecontext.format_traceparent(tid, "ef" * 8)
            r = await client.post(
                "/chat/completions",
                json={"model": "f",
                      "messages": [{"role": "user", "content": "hi"}]},
                headers={"Authorization": "Bearer k",
                         "traceparent": header})
            assert r.status_code == 200
            got, _ = tracecontext.parse_traceparent(
                r.headers["traceparent"])
            assert got == tid
            # body knob: consumed (never forwarded) and honored
            tid2 = tracecontext.new_trace_id()
            r = await client.post(
                "/chat/completions",
                json={"model": "f", "traceparent":
                      tracecontext.format_traceparent(tid2, "ab" * 8),
                      "messages": [{"role": "user", "content": "hi"}]},
                headers={"Authorization": "Bearer k"})
            assert r.status_code == 200
            got2, _ = tracecontext.parse_traceparent(
                r.headers["traceparent"])
            assert got2 == tid2
            # malformed body knob → ONE 400 up front
            r = await client.post(
                "/chat/completions",
                json={"model": "f", "traceparent": "junk",
                      "messages": [{"role": "user", "content": "hi"}]},
                headers={"Authorization": "Bearer k"})
            assert r.status_code == 400
            assert "traceparent" in r.json()["error"]["message"]

    asyncio.run(run())
