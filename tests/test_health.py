"""GET /health parity (/root/reference/tests/test_health.py)."""

from tests.conftest import make_client


async def test_health():
    async with make_client({"primary_backends": [], "settings": {}}) as client:
        r = await client.get("/health")
        assert r.status_code == 200
        assert r.json() == {"status": "healthy"}


async def test_health_v1_alias():
    async with make_client({"primary_backends": [], "settings": {}}) as client:
        r = await client.get("/v1/health")
        assert r.status_code == 200
