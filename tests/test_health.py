"""GET /health parity (/root/reference/tests/test_health.py)."""

from tests.conftest import make_client


async def test_health():
    async with make_client({"primary_backends": [], "settings": {}}) as client:
        r = await client.get("/health")
        assert r.status_code == 200
        assert r.json() == {"status": "healthy"}


async def test_health_v1_alias():
    async with make_client({"primary_backends": [], "settings": {}}) as client:
        r = await client.get("/v1/health")
        assert r.status_code == 200


async def test_models_lists_configured_ids():
    """GET /models and /v1/models: OpenAI discovery — one entry per distinct
    configured model id, owned_by naming the serving backend(s)."""
    import httpx

    from quorum_tpu.config import Config
    from quorum_tpu.server.app import create_app

    raw = {
        "settings": {"timeout": 10},
        "primary_backends": [
            {"name": "A", "url": "http://one.test/v1", "model": "gpt-4o-mini"},
            {"name": "B", "url": "http://two.test/v1", "model": "gpt-4o-mini"},
            {"name": "T", "url": "tpu://gpt2-tiny?max_seq=64", "model": ""},
        ],
    }
    app = create_app(Config(raw=raw))
    async with httpx.AsyncClient(
        transport=httpx.ASGITransport(app=app), base_url="http://t"
    ) as client:
        for path in ("/models", "/v1/models"):
            body = (await client.get(path)).json()
            assert body["object"] == "list"
            ids = {m["id"]: m for m in body["data"]}
            assert ids["gpt-4o-mini"]["owned_by"] == "A,B"
            assert "gpt2-tiny" in ids
            assert all(m["object"] == "model" for m in body["data"])
