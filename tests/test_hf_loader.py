"""HF checkpoint loader: logits parity against transformers' own forward.

The strongest possible correctness check for weight mapping: build a tiny
random HF model, save it locally (no network), load it through
quorum_tpu.models.hf_loader, and require the JAX forward to match the torch
forward to float tolerance — for gpt2 (Conv1D fused qkv, learned pos),
llama (GQA + RoPE), qwen2-style attention bias, and mixtral (top-2 MoE).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from quorum_tpu.models.hf_loader import load_hf_checkpoint, spec_from_hf_config
from quorum_tpu.models.transformer import forward_logits

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

TOKENS = np.array([[3, 17, 5, 9, 250, 11, 42, 7]], dtype=np.int32)


def torch_logits(model, tokens):
    import torch

    with torch.no_grad():
        return model(torch.tensor(tokens, dtype=torch.long)).logits.float().numpy()


def our_logits(ckpt_dir):
    spec, params = load_hf_checkpoint(ckpt_dir, dtype="float32")
    return np.asarray(forward_logits(params, spec, jnp.asarray(TOKENS)))


def assert_close(ours, theirs, atol=2e-3):
    np.testing.assert_allclose(ours, theirs, atol=atol, rtol=1e-3)


def test_gpt2_checkpoint_parity(tmp_path):
    from transformers import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config(
        vocab_size=512, n_positions=64, n_embd=32, n_layer=2, n_head=4
    )
    model = GPT2LMHeadModel(cfg).eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    assert_close(our_logits(tmp_path), torch_logits(model, TOKENS))


def test_llama_gqa_checkpoint_parity(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(cfg).eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    assert_close(our_logits(tmp_path), torch_logits(model, TOKENS))


def test_llama3_rope_scaling_parity(tmp_path):
    """The llama3 rope_scaling recipe (3.1/3.2 checkpoints) pinned
    bit-for-bit against transformers' own implementation: positions past
    the ORIGINAL context only make sense scaled, so the tiny config sets
    original_max_position_embeddings below max_seq and the probe tokens
    exercise positions in the scaled band."""
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        tie_word_embeddings=False,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 16},
    )
    model = LlamaForCausalLM(cfg).eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    spec, params = load_hf_checkpoint(tmp_path, dtype="float32")
    assert spec.rope_scaling == "llama3"
    assert spec.rope_scaling_factor == 8.0
    assert spec.rope_original_max_seq == 16
    long_tokens = np.arange(40, dtype=np.int32)[None, :] % 500 + 3
    ours = np.asarray(forward_logits(params, spec, jnp.asarray(long_tokens)))
    assert_close(ours, torch_logits(model, long_tokens))

    # The scaling is load-bearing: dropping it must change the logits.
    import dataclasses

    unscaled = dataclasses.replace(spec, rope_scaling="")
    diverged = np.asarray(
        forward_logits(params, unscaled, jnp.asarray(long_tokens)))
    assert np.abs(diverged - ours).max() > 1e-3


def test_unsupported_rope_scaling_fails_loudly(tmp_path):
    from quorum_tpu.models.hf_loader import spec_from_hf_config

    with pytest.raises(ValueError, match="rope_scaling"):
        spec_from_hf_config({
            "model_type": "llama", "vocab_size": 512, "hidden_size": 32,
            "intermediate_size": 64, "num_hidden_layers": 2,
            "num_attention_heads": 4,
            "rope_scaling": {"rope_type": "yarn", "factor": 4.0},
        })


def test_llama_attention_bias_parity(tmp_path):
    """qwen2-style attention: qkv biases present."""
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, attention_bias=True,
        tie_word_embeddings=True,
    )
    model = LlamaForCausalLM(cfg).eval()
    # transformers zero-inits biases — randomize them so the bias mapping
    # (bq/bk/bv/bo) is actually exercised, not vacuously compared against 0.
    import torch

    with torch.no_grad():
        for layer in model.model.layers:
            for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
                b = getattr(layer.self_attn, proj).bias
                if b is not None:
                    b.normal_(0.0, 0.5)
    model.save_pretrained(tmp_path, safe_serialization=True)
    assert_close(our_logits(tmp_path), torch_logits(model, TOKENS))


def test_mixtral_moe_checkpoint_parity(tmp_path):
    from transformers import MixtralConfig, MixtralForCausalLM

    cfg = MixtralConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_local_experts=4, num_experts_per_tok=2,
        tie_word_embeddings=False,
    )
    model = MixtralForCausalLM(cfg).eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    assert_close(our_logits(tmp_path), torch_logits(model, TOKENS))


def test_pytorch_bin_fallback(tmp_path):
    """Checkpoints without safetensors load via pytorch_model.bin."""
    from transformers import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config(vocab_size=512, n_positions=64, n_embd=32, n_layer=2, n_head=4)
    model = GPT2LMHeadModel(cfg).eval()
    model.save_pretrained(tmp_path, safe_serialization=False)
    assert_close(our_logits(tmp_path), torch_logits(model, TOKENS))


def test_spec_inference_fields():
    spec = spec_from_hf_config(
        {
            "model_type": "mistral",
            "vocab_size": 32000, "hidden_size": 4096,
            "intermediate_size": 14336, "num_hidden_layers": 32,
            "num_attention_heads": 32, "num_key_value_heads": 8,
            "max_position_embeddings": 8192, "rope_theta": 1000000.0,
            "rms_norm_eps": 1e-5,
        }
    )
    assert spec.family == "llama" and spec.n_kv_heads == 8
    assert spec.rope_theta == 1000000.0 and spec.act == "swiglu"
    with pytest.raises(ValueError):
        spec_from_hf_config({"model_type": "bert"})


async def test_ckpt_backend_end_to_end(tmp_path):
    """tpu://...?ckpt=<dir> serves real checkpoint weights through the full
    Backend protocol, using the checkpoint's own tokenizer when present."""
    from transformers import AutoTokenizer, GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config(vocab_size=512, n_positions=64, n_embd=32, n_layer=2, n_head=4)
    GPT2LMHeadModel(cfg).eval().save_pretrained(tmp_path, safe_serialization=True)

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    b = TpuBackend.from_spec(
        BackendSpec(name="CKPT", url=f"tpu://gpt2?ckpt={tmp_path}&max_tokens=6")
    )
    res = await b.complete({"messages": [{"role": "user", "content": "hi"}]}, {}, 60.0)
    assert res.ok and res.body["object"] == "chat.completion"
    assert res.body["usage"]["completion_tokens"] >= 1

    # two backends on one checkpoint share the engine (weights loaded once)
    b2 = TpuBackend.from_spec(BackendSpec(name="CKPT2", url=f"tpu://gpt2?ckpt={tmp_path}"))
    assert b2.engine is b.engine


async def test_ckpt_ensemble_members_diverge(tmp_path):
    """Two ckpt backends over one checkpoint share weights but must stream
    DIFFERENT samples (seed= offsets the sampling RNG, not the weights)."""
    from transformers import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config(vocab_size=512, n_positions=64, n_embd=32, n_layer=2, n_head=4)
    GPT2LMHeadModel(cfg).eval().save_pretrained(tmp_path, safe_serialization=True)

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    body = {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 12,
            "temperature": 1.0}
    outs = []
    for seed in (0, 1):
        b = TpuBackend.from_spec(
            BackendSpec(name=f"M{seed}", url=f"tpu://gpt2?ckpt={tmp_path}&seed={seed}")
        )
        res = await b.complete(dict(body), {}, 60.0)
        outs.append(res.body["choices"][0]["message"]["content"])
    m0 = TpuBackend.from_spec(BackendSpec(name="A", url=f"tpu://gpt2?ckpt={tmp_path}&seed=0"))
    m1 = TpuBackend.from_spec(BackendSpec(name="B", url=f"tpu://gpt2?ckpt={tmp_path}&seed=1"))
    assert m0.engine is m1.engine  # weights shared
    assert outs[0] != outs[1]      # samples diverge


def test_gemma_checkpoint_parity(tmp_path):
    """Gemma: GeGLU MLP, (1 + w) RMSNorm, sqrt(d_model)-scaled embeddings,
    tied lm_head — all three quirks must match transformers' forward."""
    from transformers import GemmaConfig, GemmaForCausalLM

    cfg = GemmaConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=64,
    )
    model = GemmaForCausalLM(cfg).eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    spec, _ = load_hf_checkpoint(tmp_path, dtype="float32")
    assert spec.family == "gemma" and spec.act == "geglu"
    assert spec.norm_offset == 1.0 and spec.emb_scale == 32.0 ** 0.5
    assert_close(our_logits(tmp_path), torch_logits(model, TOKENS))


def _write_chat_tokenizer(dirpath, template):
    """A tiny offline word-level HF tokenizer with a chat template."""
    import json as _json

    from tokenizers import Tokenizer as RawTokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {"<unk>": 0, "hello": 1, "world": 2, "hi": 3, "be": 4, "brief": 5}
    raw = RawTokenizer(WordLevel(vocab, unk_token="<unk>"))
    raw.pre_tokenizer = Whitespace()
    raw.save(str(dirpath / "tokenizer.json"))
    (dirpath / "tokenizer_config.json").write_text(_json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "unk_token": "<unk>",
        "chat_template": template,
    }))


def test_hf_tokenizer_applies_chat_template(tmp_path):
    """An instruct checkpoint's chat template must shape the prompt — not the
    static 'role: content' fallback (round-1 always used the fallback even
    when the checkpoint shipped a template, VERDICT.md weakness 5)."""
    from quorum_tpu.engine.tokenizer import ByteTokenizer, HFTokenizer, render_chat

    template = (
        "{% for message in messages %}<|{{ message.role }}|>"
        "{{ message.content }}{% endfor %}<|assistant|>"
    )
    _write_chat_tokenizer(tmp_path, template)
    msgs = [
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": [{"type": "text", "text": "hi"}]},
    ]
    hf = HFTokenizer(str(tmp_path))
    assert hf.render_chat(msgs) == "<|system|>be brief<|user|>hi<|assistant|>"
    # byte tokenizer (no template) keeps the deterministic fallback
    assert ByteTokenizer(512).render_chat(msgs) == render_chat(msgs)


async def test_ckpt_backend_uses_checkpoint_chat_template(tmp_path):
    """End to end: a ckpt= backend with a templated tokenizer feeds the
    templated prompt into the engine."""
    from transformers import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config(vocab_size=512, n_positions=64, n_embd=32, n_layer=2, n_head=4)
    GPT2LMHeadModel(cfg).eval().save_pretrained(tmp_path, safe_serialization=True)
    _write_chat_tokenizer(
        tmp_path,
        "{% for m in messages %}<|{{ m.role }}|>{{ m.content }}{% endfor %}<|assistant|>",
    )

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    b = TpuBackend.from_spec(
        BackendSpec(name="T", url=f"tpu://gpt2?ckpt={tmp_path}&max_tokens=4")
    )
    plan = b._plan({"messages": [{"role": "user", "content": "hello world"}]})
    assert plan["prompt_ids"] == b.tokenizer.encode("<|user|>hello world<|assistant|>")
    res = await b.complete({"messages": [{"role": "user", "content": "hello"}]}, {}, 60.0)
    assert res.ok
