"""Smoke over the host-path microbench (``make hostpath-bench``).

Runs the same entry point the Makefile target runs, at a budget small
enough for the fast tier (NOT slow-marked — this is the CPU-measurable
proof of the decode-dispatch pipeline, wired into every suite run), and
pins the dispatch accounting the bench reports:

  - strictly fewer blocking host syncs per request at K=4 than K=1 for a
    >=8-chunk generation (the ISSUE acceptance counter check)
  - zero overrun tokens when rows finish on device
  - token-for-token identical output across depths
"""

from scripts.hostpath_bench import run


def test_hostpath_bench_counters():
    m = run(tokens=32, chunk=4, depth=4, repeats=1)
    assert m["k1_dispatches_per_request"] >= 8
    assert m["k4_syncs_per_request"] < m["k1_syncs_per_request"]
    assert m["k1_overrun_tokens"] == 0
    assert m["k4_overrun_tokens"] == 0
    assert m["tokens_match"] is True
    assert 0.0 <= m["host_turnaround_share"] < 1.0
