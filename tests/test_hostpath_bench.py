"""Smoke over the host-path microbench (``make hostpath-bench``).

Runs the same entry point the Makefile target runs, at a budget small
enough for the fast tier (NOT slow-marked — this is the CPU-measurable
proof of the decode-dispatch pipeline and the megachunk decode loop, wired
into every suite run), and pins the dispatch accounting the bench reports:

  - strictly fewer blocking host syncs per request at K=4 than K=1 for a
    >=8-chunk generation (the ISSUE acceptance counter check)
  - dispatches/request reduced ~C× at decode_loop=C with blocking
    syncs/request still <= 1 (the megachunk acceptance)
  - zero overrun tokens when rows finish on device
  - token-for-token identical output across depths AND fusion
  - the prefill-interference legs (colocated vs colocated+zero_drain vs
    disagg=1+1, ISSUE 11) produce the streamed tokens identically with a
    live device→device KV handoff on the disagg arm and zero admission
    stall on the zero-drain arm (the p99-gap ORDERING is the bench's
    printed acceptance number, not a suite assertion — wall-clock
    percentiles on a shared CI core flake)
  - the speculative A/B legs (ISSUE 10): acceptance rate > 0 on the
    repetitive AND the constrained repetitive leg, tokens identical spec
    on vs off, verify turns overlapping the ring (tok/s ORDERING is the
    printed number — wall-clock on a shared CI core flakes)
"""

from scripts.hostpath_bench import (dedup, interference, paged, qos, run,
                                    sharded, spec)


def test_hostpath_bench_counters():
    m = run(tokens=32, chunk=4, depth=4, repeats=1, loop=4)
    assert m["k1_dispatches_per_request"] >= 8
    assert m["k4_syncs_per_request"] < m["k1_syncs_per_request"]
    assert m["k1_overrun_tokens"] == 0
    assert m["k4_overrun_tokens"] == 0
    assert m["loop4_overrun_tokens"] == 0
    # Megachunk acceptance: one dispatch covers ~C chunks (8 chunks at
    # C=4 → 2-3 dispatches), and the host still blocks at most about once
    # per request (the first dispatch of each generation).
    assert m["loop4_dispatches_per_request"] <= m["k1_dispatches_per_request"] / 2
    assert m["loop4_syncs_per_request"] <= 1.5
    assert m["loop_dispatch_reduction"] >= 2.0
    assert m["tokens_match"] is True
    assert 0.0 <= m["host_turnaround_share"] < 1.0
    assert m["loop4_drain_gap_ms_per_dispatch"] >= 0.0
    # Per-family device-seconds attribution (ISSUE 12): the unfused legs'
    # decode time lives under "plain", the megachunk leg's under "loop",
    # with sane percentiles from the engine's LatencyModel reservoir.
    assert "plain" in m["k1_device_seconds"], m["k1_device_seconds"]
    assert "loop" in m["loop4_device_seconds"], m["loop4_device_seconds"]
    for leg in ("k1", "k4", "loop4"):
        for fam, stats in m[f"{leg}_device_seconds"].items():
            assert stats["count"] > 0, (leg, fam)
            assert 0.0 <= stats["p50_ms"] <= stats["p99_ms"], (leg, fam)


def test_spec_bench_smoke():
    m = spec(tokens=24, chunk=4, depth=4, g=4)
    for leg in ("rep", "crep"):
        assert m[f"spec_{leg}_tokens_match"] is True, leg
        assert m[f"spec_{leg}_on_acceptance"] > 0.0, (leg, m)
        assert m[f"spec_{leg}_on_spec_turns"] > 0, (leg, m)
        # ring-resident verify: speculative dispatches overlap the ring
        assert m[f"spec_{leg}_on_spec_overlapped"] > 0, (leg, m)
        # fewer dispatches than the spec-off arm for the same tokens (the
        # wall-clock speedup is the printed number; dispatch counts are
        # the machine-stable form of the same win)
        assert (m[f"spec_{leg}_on_dispatches_per_request"]
                < m[f"spec_{leg}_off_dispatches_per_request"]), (leg, m)


def test_interference_bench_smoke():
    m = interference(tokens=24, chunk=4, depth=4, loop=4, churn=2,
                     churn_prompt_tokens=40)
    for tag in ("colocated", "zero_drain", "disagg"):
        for p in ("p50", "p95", "p99"):
            assert m[f"{tag}_intertoken_{p}_ms"] >= 0.0
    # The disagg leg really ran disaggregated: its stream equals the
    # colocated stream token for token, and KV crossed the group boundary.
    assert m["interference_tokens_match"] is True
    assert m["disagg_kv_handoffs"] >= 1
    assert m["disagg_kv_handoff_bytes"] > 0
    # The zero-drain leg really injected: zero admission stall
    # (structurally — pressure never clamps the ring), zero handoff bytes
    # (one device group), and the p99 ratios are finite numbers (their
    # ORDERING is the bench's printed acceptance; wall-clock percentiles
    # on a shared CI core flake).
    assert m["zero_drain_admission_stall_s"] == 0.0
    assert m["zero_drain_p99_vs_disagg"] >= 0.0
    assert m["zero_drain_p99_vs_colocated"] >= 0.0
    assert m["zero_drain_admission_overlap"] >= 0
    # Per-family device-seconds per arm (ISSUE 12): every arm decoded
    # fused megachunks ("loop"), and the staged arms' injection programs
    # attributed under the handoff write family.
    for tag in ("colocated", "zero_drain", "disagg"):
        assert "loop" in m[f"{tag}_device_seconds"], (
            tag, m[f"{tag}_device_seconds"])
    assert "hput" in m["zero_drain_device_seconds"]
    assert "hput" in m["disagg_device_seconds"]


def test_sharded_bench_smoke():
    """The per-group-sharding legs (ISSUE 14): all three arms stream
    token-for-token identical output at matched device count, the
    disagg arms move KV across the group boundary (the tp arm via the
    on-the-fly reshard route), and the staged arm's decode time is
    attributed under its own pp_* program families (tok/s ORDERING is
    the bench's printed number — wall-clock on a shared CI core flakes)."""
    m = sharded(tokens=16, chunk=4, depth=2, loop=2, repeats=1)
    assert m["sharded_tokens_match"] is True
    for tag in ("disagg_tp2", "disagg_pp2"):
        assert m[f"sharded_{tag}_handoff_bytes"] > 0, (tag, m)
        assert m[f"sharded_{tag}_handoff_bytes_per_s"] > 0, (tag, m)
    assert m["sharded_colocated_tp4_handoff_bytes"] == 0
    assert m["sharded_disagg_pp2_decode_pp"] == 2
    fams = m["sharded_disagg_pp2_device_seconds"]
    assert any(f.startswith("pp_") for f in fams), fams
    assert not any(f.startswith("pp_")
                   for f in m["sharded_colocated_tp4_device_seconds"])
    for tag in ("colocated_tp4", "disagg_tp2", "disagg_pp2"):
        assert m[f"sharded_{tag}_tok_s"] > 0
        assert m[f"sharded_{tag}_dispatches_per_request"] > 0


def test_paged_bench_smoke():
    """The paged-KV rows-per-chip legs (ISSUE 17): at a fixed cache
    position budget the paged engine keeps strictly more short streams
    resident than the dense rectangle's slot count, fills the page pool,
    and every stream's tokens match its dense twin (the >= 4x ratio is
    the bench's printed acceptance gate; the suite asserts the ordering
    — peak concurrency sampling on a shared CI core flakes)."""
    m = paged(tokens=8, streams=24, page_size=16, pool_pages=32)
    assert m["paged_tokens_match"] is True
    assert m["paged_dense_completed"] == m["paged_paged_completed"] == 24
    # the fixed budget buys the dense arm max_seq-sized rows only
    assert m["paged_dense_peak_rows"] <= m["paged_dense_rows"]
    # strictly more rows resident at once under paging, pool never over-
    # committed (admission pre-reserves each row's whole span)
    assert m["paged_paged_peak_rows"] > m["paged_dense_rows"]
    assert m["paged_rows_per_chip_ratio"] >= 2.0
    assert 0.0 < m["paged_peak_page_occupancy"] <= 1.0


def test_qos_bench_smoke():
    """The QoS scheduler A/B legs (ISSUE 18, docs/scheduling.md): both
    arms complete mixed interactive+batch churn, preemptions fire on the
    qos arm with every parked token replayed (token-exactness itself is
    pinned by tests/test_sched.py), and the ratios are finite numbers
    (the fifo/qos p99 ORDERING is the bench's printed acceptance —
    wall-clock percentiles on a shared CI core flake)."""
    m = qos(tokens=24, churn=3, arrivals=4)
    for tag in ("fifo", "qos"):
        assert m[f"qos_{tag}_interactive_ttft_p50_ms"] >= 0.0
        assert m[f"qos_{tag}_interactive_ttft_p99_ms"] >= \
            m[f"qos_{tag}_interactive_ttft_p50_ms"] - 1e-9
        assert m[f"qos_{tag}_churn_streams"] > 0
        assert m[f"qos_{tag}_churn_tok_s"] > 0
    assert m["qos_solo_ttft_p50_ms"] >= 0.0
    # The qos arm really scheduled: preemptions fired and every parked
    # token was regenerated through the replay guard.
    assert m["qos_preemptions"] >= 1, m
    assert m["qos_preempted_tokens"] >= 1
    assert m["qos_replayed_tokens"] == m["qos_preempted_tokens"]
    assert m["qos_ttft_p99_ratio"] > 0.0
    assert m["qos_batch_degradation"] > 0.0


def test_dedup_bench_smoke():
    """The shared-prefix member dedup A/B leg (docs/quorum.md): dedup-on
    output stays token-for-token identical to dedup-off, every coalesced
    fan-out saves exactly (members-1)*prompt_len prefill tokens, and the
    reported ratio reflects a real reduction (the WALL ordering is the
    bench's printed acceptance — wall-clock on a shared CI core flakes)."""
    m = dedup(prompt_len=24, tokens=4, members=3, rounds=4)
    assert m["dedup_tokens_match"] is True
    assert 1 <= m["dedup_rounds"] <= m["dedup_rounds_driven"]
    # Exact per-admission savings arithmetic: each coalesced fan-out
    # prefills the prompt once instead of `members` times.
    assert (m["dedup_off_prefill_tokens"] - m["dedup_on_prefill_tokens"]
            == m["dedup_rounds"] * (3 - 1) * 24)
    assert m["dedup_prefill_token_ratio"] > 1.0
    assert m["dedup_off_wall_s"] >= 0.0 and m["dedup_on_wall_s"] >= 0.0
