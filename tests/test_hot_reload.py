"""Dev-mode config hot reload (quorum_tpu/server/reload.py).

Reference parity target: its dev server restarts the whole process on
``config.yaml`` edits (/root/reference/Makefile:4, uvicorn
``--reload-include "*.yaml"``). Here reload is in-process and incremental —
a config edit changes routing on the NEXT request, live ``tpu://`` engines
survive edits that don't touch them, and a malformed edit keeps the previous
config serving (VERDICT r3 next-round item 8).
"""

import asyncio
import os
import time

import httpx
import yaml

from quorum_tpu.config import load_config
from quorum_tpu.server.app import create_app

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def _write(path, raw):
    path.write_text(yaml.safe_dump(raw))
    # The watcher signature is (mtime_ns, size); same-size rewrites within
    # one mtime granule are possible on fast filesystems — nudge mtime so
    # every test edit is observable.
    st = os.stat(path)
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))


def _cfg(backends, timeout=120):
    return {
        "settings": {"timeout": timeout},
        "primary_backends": backends,
    }


def _tiny(name, seed, extra=""):
    return {"name": name,
            "url": f"tpu://llama-tiny?seed={seed}&max_seq=256&slots=2"
                   f"&max_tokens=4{extra}",
            "model": "tiny"}


def _client(app):
    return httpx.AsyncClient(transport=httpx.ASGITransport(app=app),
                             base_url="http://testserver")


async def _wait_reload_window():
    # The watcher rate-limits stat() to one per 0.5 s window.
    await asyncio.sleep(0.6)


async def test_edit_changes_routing_and_keeps_live_engine(tmp_path):
    path = tmp_path / "config.yaml"
    _write(path, _cfg([_tiny("A", seed=1)]))
    cfg = load_config(path)
    assert cfg.source_path == path
    app = create_app(cfg, watch_config=True)

    async with _client(app) as client:
        body = {"model": "tiny", "max_tokens": 4, "temperature": 0.0,
                "messages": [{"role": "user", "content": "reload probe"}]}
        r1 = await client.post("/v1/chat/completions", json=body,
                               headers={"Authorization": "Bearer t"})
        assert r1.status_code == 200 and r1.json()["backend"] == "A"
        engine_before = app.state["registry"].get("A").engine

        # Rename the backend (same tpu:// URL) — routing must change on the
        # next request, and the SAME backend-instance/engine must NOT be
        # rebuilt... the name changed, so the instance is reconstructed, but
        # the engine cache re-attaches it to the live weights.
        _write(path, _cfg([_tiny("B", seed=1)]))
        await _wait_reload_window()
        r2 = await client.post("/v1/chat/completions", json=body,
                               headers={"Authorization": "Bearer t"})
        assert r2.status_code == 200 and r2.json()["backend"] == "B"
        models = (await client.get("/v1/models")).json()
        assert models["data"][0]["owned_by"] == "B"
        engine_after = app.state["registry"].get("B").engine
        assert engine_after is engine_before, (
            "unchanged tpu:// URL must keep serving from the live engine")


async def test_unchanged_backend_instance_is_reused(tmp_path):
    path = tmp_path / "config.yaml"
    _write(path, _cfg([_tiny("A", seed=1)], timeout=120))
    cfg = load_config(path)
    app = create_app(cfg, watch_config=True)

    async with _client(app) as client:
        body = {"model": "tiny", "max_tokens": 4, "temperature": 0.0,
                "messages": [{"role": "user", "content": "x"}]}
        await client.post("/v1/chat/completions", json=body,
                          headers={"Authorization": "Bearer t"})
        backend_before = app.state["registry"].get("A")

        # Edit only the timeout: the backend identity (name, url, model) is
        # untouched → the very INSTANCE survives the reload.
        _write(path, _cfg([_tiny("A", seed=1)], timeout=77))
        await _wait_reload_window()
        await client.get("/v1/models")
        assert app.state["registry"].get("A") is backend_before
        assert app.state["config"].timeout == 77.0


async def test_malformed_edit_keeps_previous_config(tmp_path):
    path = tmp_path / "config.yaml"
    _write(path, _cfg([_tiny("A", seed=1)]))
    app = create_app(load_config(path), watch_config=True)

    async with _client(app) as client:
        body = {"model": "tiny", "max_tokens": 4, "temperature": 0.0,
                "messages": [{"role": "user", "content": "x"}]}
        r1 = await client.post("/v1/chat/completions", json=body,
                               headers={"Authorization": "Bearer t"})
        assert r1.status_code == 200

        path.write_text("primary_backends: [:::not yaml")
        st = os.stat(path)
        os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
        await _wait_reload_window()
        r2 = await client.post("/v1/chat/completions", json=body,
                               headers={"Authorization": "Bearer t"})
        assert r2.status_code == 200 and r2.json()["backend"] == "A"

        # ...and a subsequent good edit applies cleanly.
        _write(path, _cfg([_tiny("C", seed=1)]))
        await _wait_reload_window()
        r3 = await client.post("/v1/chat/completions", json=body,
                               headers={"Authorization": "Bearer t"})
        assert r3.status_code == 200 and r3.json()["backend"] == "C"


async def test_valid_yaml_bad_shape_keeps_previous_config(tmp_path):
    """A config that parses as YAML but has a malformed backends shape
    (scalar entries) must behave like a YAML typo: previous config keeps
    serving, the triggering request succeeds, no crash."""
    path = tmp_path / "config.yaml"
    _write(path, _cfg([_tiny("A", seed=1)]))
    app = create_app(load_config(path), watch_config=True)

    async with _client(app) as client:
        body = {"model": "tiny", "max_tokens": 4, "temperature": 0.0,
                "messages": [{"role": "user", "content": "x"}]}
        assert (await client.post("/v1/chat/completions", json=body,
                                  headers={"Authorization": "Bearer t"})
                ).status_code == 200
        path.write_text("primary_backends:\n  - just-a-string\n")
        st = os.stat(path)
        os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
        await _wait_reload_window()
        r = await client.post("/v1/chat/completions", json=body,
                              headers={"Authorization": "Bearer t"})
        assert r.status_code == 200 and r.json()["backend"] == "A"


async def test_dropped_engine_is_released(tmp_path):
    """An edit that drops a tpu:// backend (weights no longer referenced)
    must shut its engine down and evict it from the shared cache — not
    leak HBM-scale state behind a no-op aclose."""
    from quorum_tpu.engine.engine import _ENGINES

    path = tmp_path / "config.yaml"
    _write(path, _cfg([_tiny("A", seed=41)]))
    app = create_app(load_config(path), watch_config=True)

    async with _client(app) as client:
        body = {"model": "tiny", "max_tokens": 4, "temperature": 0.0,
                "messages": [{"role": "user", "content": "x"}]}
        await client.post("/v1/chat/completions", json=body,
                          headers={"Authorization": "Bearer t"})
        old_engine = app.state["registry"].get("A").engine
        assert any(e is old_engine for e in _ENGINES.values())

        # different seed = different weights: the old engine has no keeper
        _write(path, _cfg([_tiny("A", seed=42)]))
        await _wait_reload_window()
        r = await client.post("/v1/chat/completions", json=body,
                              headers={"Authorization": "Bearer t"})
        assert r.status_code == 200
        new_engine = app.state["registry"].get("A").engine
        assert new_engine is not old_engine
        assert not any(e is old_engine for e in _ENGINES.values()), (
            "dropped engine still in the shared cache")


async def test_watch_off_by_default(tmp_path):
    path = tmp_path / "config.yaml"
    _write(path, _cfg([_tiny("A", seed=1)]))
    app = create_app(load_config(path))  # no watch_config, no env toggle

    async with _client(app) as client:
        _write(path, _cfg([_tiny("B", seed=1)]))
        await _wait_reload_window()
        models = (await client.get("/v1/models")).json()
        assert models["data"][0]["owned_by"] == "A"
