"""int8 KV cache (``kv_quant=int8``): accuracy, capacity, and engine paths.

Representation contract (models/transformer.py): each cache side becomes
``(int8 values, f32 per-token scales)`` with ``value ≈ q8 * scale``; decode
attention contracts natively in int8 (ops.attention.decode_attention_q8 —
never dequantize-into-dot, the measured lesson from weight quant, PERF.md
§2), while the cold prefill-segment/verify paths dequantize their bounded
history window.
"""

import jax
import jax.numpy as jnp
import numpy as np

from quorum_tpu.backends.tpu_backend import TpuBackend
from quorum_tpu.config import BackendSpec
from quorum_tpu.engine.engine import InferenceEngine, get_engine
from quorum_tpu.models.model_config import MODEL_PRESETS, resolve_spec
from quorum_tpu.models.transformer import init_cache
from quorum_tpu.ops.attention import (
    decode_attention,
    decode_attention_q8,
    quantize_rows,
)
from quorum_tpu.ops.sampling import SamplerConfig

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

TINY = MODEL_PRESETS["llama-tiny"]


def test_q8_decode_attention_close_to_dense():
    """Native-int8 decode attention must track the bf16 path within the
    int8 quantization noise floor on random caches."""
    rng = np.random.default_rng(0)
    b, h, kh, t, hd = 2, 4, 2, 32, 16
    q = jnp.asarray(rng.normal(size=(b, h, 1, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, kh, t, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, kh, t, hd)), jnp.float32)
    length = jnp.asarray([t, t // 2], jnp.int32)

    ref = decode_attention(q, k, v, length)
    k8, ks = quantize_rows(k, axis=-1)
    v8, vs = quantize_rows(v, axis=-1)
    got = decode_attention_q8(q, k8, ks[..., 0], v8, vs[..., 0], length)

    err = float(jnp.linalg.norm(got - ref) / jnp.linalg.norm(ref))
    assert err < 0.05, f"int8 KV attention error {err:.4f} exceeds 5%"


def test_kv_cache_int8_half_bytes():
    ck_bf, cv_bf = init_cache(TINY, batch=2)
    ck_q8, cv_q8 = init_cache(TINY, batch=2, kv_quant="int8")
    bf_bytes = ck_bf.nbytes + cv_bf.nbytes
    q8_bytes = sum(x.nbytes for x in jax.tree.leaves((ck_q8, cv_q8)))
    # int8 values are half of bf16; the f32 per-token scale adds 4 bytes per
    # 2·head_dim bf16 bytes → ratio 0.5 + 2/head_dim (1.6% at hd=128; the
    # tiny spec's hd=16 pays 12.5%)
    assert q8_bytes <= (0.5 + 2 / TINY.head_dim + 0.001) * bf_bytes
    assert ck_q8[0].dtype == jnp.int8 and ck_q8[1].dtype == jnp.float32


def test_engine_kv_quant_generates_and_first_token_matches():
    """The admission prefill attends over the ORIGINAL bf16 k/v (the cache
    write is separate), so the first sampled token must match the bf16-cache
    engine exactly; later tokens may drift within quantization noise but the
    generation must complete its budget."""
    eng_bf = InferenceEngine(TINY, seed=0, decode_chunk=4, n_slots=2)
    eng_q8 = InferenceEngine(TINY, seed=0, decode_chunk=4, n_slots=2,
                             kv_quant="int8")
    prompt = [3, 4, 5, 6]
    out_bf = eng_bf.generate(prompt, max_new_tokens=8,
                             sampler=SamplerConfig(temperature=0.0)).token_ids
    out_q8 = eng_q8.generate(prompt, max_new_tokens=8,
                             sampler=SamplerConfig(temperature=0.0)).token_ids
    assert len(out_q8) == 8
    assert out_q8[0] == out_bf[0]
    assert all(0 <= t < TINY.vocab_size for t in out_q8)


def test_kv_quant_chunked_prefill_and_prefix_reuse_exact():
    """Long prompts ride chunked prefill with a quantized cache, and prefix
    reuse stays EXACT within the representation: a warm request reusing
    resident int8 rows matches the cold kv_quant engine token-for-token
    (identical stored bytes → identical reads)."""
    spec = resolve_spec("llama-tiny", {"max_seq": "128"})
    cold = InferenceEngine(spec, seed=2, decode_chunk=4, n_slots=1,
                           prefill_chunk=16, kv_quant="int8",
                           prefix_cache=False)
    warm = InferenceEngine(spec, seed=2, decode_chunk=4, n_slots=1,
                           prefill_chunk=16, kv_quant="int8")
    prompt = [(7 + 3 * i) % 500 for i in range(50)]
    follow = prompt + [9, 8, 7]

    kw = dict(max_new_tokens=6, sampler=SamplerConfig(temperature=0.7),
              seed=4)
    want_first = cold.generate(prompt, **kw).token_ids
    want_follow = cold.generate(follow, **kw).token_ids
    got_first = warm.generate(prompt, **kw).token_ids   # cold in warm engine
    got_follow = warm.generate(follow, **kw).token_ids  # reuses prefix rows
    assert got_first == want_first
    assert got_follow == want_follow
    assert warm.prefix_hits >= 1


def test_kv_quant_engine_on_mesh():
    """The (int8, scale) cache under GSPMD: values shard like the bf16 cache
    and the scale array drops the head_dim axis — the full engine path on a
    dp×tp mesh must still generate, and its first token (sampled from the
    bf16 prefill logits) must match the single-device kv_quant engine."""
    from quorum_tpu.parallel import MeshConfig, make_mesh

    spec = resolve_spec("llama-tiny", {"n_kv_heads": "4"})
    eng_1 = InferenceEngine(spec, seed=3, decode_chunk=4, n_slots=2,
                            kv_quant="int8")
    eng_m = InferenceEngine(spec, make_mesh(MeshConfig(dp=2, tp=4)), seed=3,
                            decode_chunk=4, n_slots=2, kv_quant="int8")
    kw = dict(max_new_tokens=8, sampler=SamplerConfig(temperature=0.0))
    one = eng_1.generate([7, 8, 9], **kw).token_ids
    sharded = eng_m.generate([7, 8, 9], **kw).token_ids
    assert len(sharded) == 8
    # full token-for-token equality (same bar as the bf16 sibling test,
    # test_engine_mesh.py): int8 rounding happens before the cache write,
    # so sharded and single-device decode read identical stored bytes
    assert sharded == one


def test_kv_quant_url_and_engine_identity():
    def mk(url):
        return TpuBackend.from_spec(BackendSpec(name="b", url=url, model="t"))

    b1 = mk("tpu://llama-tiny?kv_quant=int8&seed=700")
    b2 = mk("tpu://llama-tiny?kv_quant=int8&seed=700")
    b3 = mk("tpu://llama-tiny?seed=700")
    assert b1.engine is b2.engine
    assert b1.engine is not b3.engine
    assert b1.engine.kv_quant == "int8" and b3.engine.kv_quant is None


def test_kv_quant_composes_with_members_and_ensemble():
    """The (int8, scale) cache under the member axis: both stacked fan-out
    (members=M, separate streams) and consensus decoding (ensemble=M, one
    averaged stream) vmap over tuple-leaf caches. Member streams must still
    match the members=1 kv_quant engine with that member's seed."""
    stacked = InferenceEngine(TINY, seed=0, members=2, decode_chunk=4,
                              n_slots=2, kv_quant="int8")
    singles = [InferenceEngine(TINY, seed=i, decode_chunk=4, n_slots=2,
                               kv_quant="int8") for i in range(2)]
    kw = dict(max_new_tokens=6,
              sampler=SamplerConfig(temperature=0.8, top_p=0.9), seed=4)
    got = [stacked.generate([3, 4, 5], member=m, **kw).token_ids
           for m in range(2)]
    want = [singles[i].generate([3, 4, 5], **kw).token_ids for i in range(2)]
    assert got == want

    consensus = InferenceEngine(TINY, seed=0, ensemble=2, decode_chunk=4,
                                n_slots=1, kv_quant="int8")
    out = consensus.generate([5, 6], max_new_tokens=6,
                             sampler=SamplerConfig(temperature=0.0)).token_ids
    assert len(out) == 6
    assert all(0 <= t < TINY.vocab_size for t in out)


def test_kv_quant_composes_with_weight_quant():
    """quant=int8 (weights) + kv_quant=int8 (cache) together: the smallest
    serving footprint — generation still completes and emits valid ids."""
    eng = InferenceEngine(TINY, seed=1, decode_chunk=4, n_slots=2,
                          quant="int8", kv_quant="int8")
    out = eng.generate([5, 6, 7], max_new_tokens=8,
                       sampler=SamplerConfig(temperature=0.8, top_p=0.9),
                       seed=3).token_ids
    assert len(out) == 8
    assert all(0 <= t < TINY.vocab_size for t in out)
