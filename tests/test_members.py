"""Stacked fan-out members: M weight sets, M separate streams, one dispatch.

Contract (quorum_tpu/engine/engine.py ``members=M``): member i of a stacked
engine produces token-for-token the stream a ``members=1`` engine with seed
``base+i`` produces. Slot co-location, coalesced admission, and the member
vmap must never change *content* — only how many host dispatches the quorum
costs. (The reference cannot co-locate models at all: its "members" are
separate HTTP services, /root/reference/src/quorum/oai_proxy.py:182-192.)
"""

from concurrent.futures import ThreadPoolExecutor

import pytest

from quorum_tpu.backends.tpu_backend import TpuBackend
from quorum_tpu.config import BackendSpec
from quorum_tpu.engine.engine import InferenceEngine, get_engine
from quorum_tpu.models.model_config import MODEL_PRESETS, resolve_spec
from quorum_tpu.ops.sampling import SamplerConfig

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

TINY = MODEL_PRESETS["llama-tiny"]
M = 3


def _gen(eng, member, seed, prompt, n=8, temp=0.8):
    return eng.generate(
        prompt, max_new_tokens=n,
        sampler=SamplerConfig(temperature=temp, top_p=0.9),
        seed=seed, member=member,
    ).token_ids


def test_members_match_single_engines():
    """Each member's stream equals the members=1 engine with that seed."""
    stacked = InferenceEngine(TINY, seed=0, members=M, decode_chunk=4, n_slots=2)
    singles = [
        InferenceEngine(TINY, seed=i, decode_chunk=4, n_slots=2)
        for i in range(M)
    ]
    prompt = [3, 4, 5]
    want = [_gen(singles[i], 0, 7, prompt) for i in range(M)]
    got = [_gen(stacked, i, 7, prompt) for i in range(M)]
    assert got == want
    assert len({tuple(w) for w in want}) > 1, (
        "distinct member weights should usually diverge — if not, the "
        "equivalence above proved nothing")


def test_members_concurrent_matches_serial():
    """Fan-out shape: one request per member at once, co-batched in one
    program, must match the serial member-by-member runs."""
    eng = InferenceEngine(TINY, seed=0, members=M, decode_chunk=4, n_slots=2)
    jobs = [(m, 11 + m, [5, 6, 7 + m]) for m in range(M)]
    serial = [_gen(eng, *j) for j in jobs]
    with ThreadPoolExecutor(max_workers=M) as ex:
        concurrent = list(ex.map(lambda j: _gen(eng, *j), jobs))
    assert concurrent == serial


def test_member_isolation_mid_generation():
    """Admitting one member while another is mid-generation must not
    disturb the in-flight member's stream (write_gate correctness)."""
    eng = InferenceEngine(TINY, seed=0, members=2, decode_chunk=2, n_slots=2)
    solo = _gen(eng, 0, 3, [9, 8, 7], n=12)

    it = eng.generate_stream(
        [9, 8, 7], max_new_tokens=12,
        sampler=SamplerConfig(temperature=0.8, top_p=0.9), seed=3, member=0,
    )
    head = [next(it) for _ in range(2)]
    # admit member 1 into the same slot row while member 0 is active
    other = _gen(eng, 1, 4, [1, 2], n=6)
    tail = list(it)
    assert head + tail == solo
    assert len(other) == 6


def test_more_requests_than_slots_per_member():
    eng = InferenceEngine(TINY, seed=0, members=2, decode_chunk=4, n_slots=1)
    with ThreadPoolExecutor(max_workers=4) as ex:
        results = list(ex.map(
            lambda i: _gen(eng, i % 2, i, [5, 6], n=5), range(4)))
    assert all(len(r) == 5 for r in results)


def test_members_chunked_prefill_matches_single_engines():
    """Long prompts on a stacked engine ride member-coalesced chunked
    prefill (one vmapped segment program per scheduler turn) and must still
    match the per-seed engines token-for-token — including when both
    members admit the same long prompt concurrently (the fan-out shape)."""
    spec = resolve_spec("llama-tiny", {"max_seq": "128"})
    stacked = InferenceEngine(spec, seed=0, members=2, decode_chunk=4,
                              n_slots=2, prefill_chunk=16)
    singles = [InferenceEngine(spec, seed=i, decode_chunk=4, n_slots=2,
                               prefill_chunk=16) for i in range(2)]
    prompt = [(3 + 7 * i) % 500 for i in range(50)]  # > prefill_chunk
    kw = dict(max_new_tokens=6, sampler=SamplerConfig(temperature=0.7),
              seed=5)
    want = [singles[i].generate(prompt, **kw).token_ids for i in range(2)]
    with ThreadPoolExecutor(max_workers=2) as ex:
        got = list(ex.map(
            lambda m: stacked.generate(prompt, member=m, **kw).token_ids,
            range(2)))
    assert got == want


def test_members_prefix_reuse_exact_and_counted():
    """Warm turns on a stacked engine reuse each member's own resident
    rows: output matches a reuse-disabled stacked engine exactly and the
    hit counter advances once per member."""
    spec = resolve_spec("llama-tiny", {"max_seq": "128"})
    eng = InferenceEngine(spec, seed=0, members=2, decode_chunk=4,
                          n_slots=1, prefill_chunk=16)
    cold = InferenceEngine(spec, seed=0, members=2, decode_chunk=4,
                           n_slots=1, prefill_chunk=16, prefix_cache=False)
    prompt = [(9 + 3 * i) % 500 for i in range(40)]
    follow = prompt + [7, 8, 9]
    kw = dict(max_new_tokens=5, sampler=SamplerConfig(temperature=0.6),
              seed=2)
    for m in range(2):
        assert eng.generate(prompt, member=m, **kw).token_ids == \
            cold.generate(prompt, member=m, **kw).token_ids
    hits0 = eng.prefix_hits
    for m in range(2):
        assert eng.generate(follow, member=m, **kw).token_ids == \
            cold.generate(follow, member=m, **kw).token_ids
    assert eng.prefix_hits >= hits0 + 2


def test_members_logprobs_and_choices():
    """logprobs and n>1 choices ride the members path unchanged."""
    eng = InferenceEngine(TINY, seed=0, members=2, decode_chunk=4, n_slots=2)
    req = eng.submit([4, 5, 6], max_new_tokens=4, seed=9,
                     sampler=SamplerConfig(temperature=0.0),
                     logprobs=3, member=1)
    toks = list(eng.stream_results(req))
    assert len(toks) == 4
    assert len(req.lp) >= len(toks)
    lp, top_ids, top_lps = req.lp[0]
    assert lp <= 0.0 and len(top_ids) >= 3


async def test_stacked_two_hop_aggregation():
    """The reference's flagship workflow on ONE stacked engine: fan out to
    two members, then synthesize via a THIRD member as the aggregator —
    three weight sets, two hops, zero network, one engine's programs."""
    from tests.conftest import make_client

    url = "tpu://llama-tiny?members=3&member={}&slots=2&max_seq=64"
    raw = {
        "settings": {"timeout": 120},
        "primary_backends": [
            {"name": "A", "url": url.format(0), "model": "m"},
            {"name": "B", "url": url.format(1), "model": "m"},
            {"name": "AGG", "url": url.format(2), "model": "m"},
        ],
        "iterations": {"aggregation": {"strategy": "aggregate"}},
        "strategy": {
            "concatenate": {"separator": "\n---\n"},
            "aggregate": {
                "source_backends": ["A", "B"],
                "aggregator_backend": "AGG",
                "intermediate_separator": "@@SEP@@",
                "include_source_names": False,
                "suppress_individual_responses": True,
            },
        },
    }
    async with make_client(raw) as client:
        resp = await client.post(
            "/chat/completions",
            json={"model": "m", "max_tokens": 6, "temperature": 0,
                  "messages": [{"role": "user", "content": "hello"}]},
            headers={"Authorization": "Bearer x"},
        )
    assert resp.status_code == 200
    content = resp.json()["choices"][0]["message"]["content"]
    # a separator in the output would mean the join fallback ran instead of
    # the member-2 aggregation hop
    assert "@@SEP@@" not in content
    assert content


def test_stacked_engine_survives_poisoned_state():
    """_fail_all on a stacked engine: waiting consumers get the error, the
    member-stacked device state rebuilds, and the engine serves again."""
    eng = InferenceEngine(TINY, seed=0, members=2, decode_chunk=4, n_slots=2)
    before = _gen(eng, 1, 5, [4, 5, 6])
    eng._fail_all(RuntimeError("injected device poison"))
    after = _gen(eng, 1, 5, [4, 5, 6])
    assert after == before  # fresh state, same seeds → same stream
    assert eng.n_failures >= 0


def test_member_sampler_state_isolation():
    """Per-member sampler state must not leak across the coalesced
    admission: a logit_bias that forces member 0 onto one token leaves
    member 1's stream exactly as it would be without any sibling."""
    import numpy as np

    eng = InferenceEngine(TINY, seed=0, members=2, decode_chunk=4, n_slots=1)
    kw = dict(max_new_tokens=5,
              sampler=SamplerConfig(temperature=0.8, top_p=0.9))
    baseline = list(eng.stream_results(
        eng.submit([4, 5, 6], seed=3, member=1, **kw)))

    forced = 7
    bias = np.zeros((TINY.vocab_size,), np.float32)
    bias[forced] = 100.0
    from concurrent.futures import ThreadPoolExecutor as _TPE
    with _TPE(max_workers=2) as ex:
        f0 = ex.submit(lambda: list(eng.stream_results(eng.submit(
            [4, 5, 6], seed=3, member=0, logit_bias=bias, **kw))))
        f1 = ex.submit(lambda: list(eng.stream_results(eng.submit(
            [4, 5, 6], seed=3, member=1, **kw))))
        biased0, plain1 = f0.result(), f1.result()
    assert all(t == forced for t in biased0), "bias must dominate member 0"
    assert plain1 == baseline, "sibling's bias leaked into member 1"


def test_member_out_of_range_and_exclusions():
    eng = InferenceEngine(TINY, seed=0, members=2, n_slots=1)
    with pytest.raises(ValueError, match="member 5 out of range"):
        eng.submit([1, 2], max_new_tokens=2, member=5)
    with pytest.raises(ValueError, match="mutually exclusive"):
        InferenceEngine(TINY, members=2, ensemble=2)


def test_members_speculative_decoding():
    """Speculative verification on a stacked engine: greedy members with
    repetitive prompts must finish in FEWER dispatches than tokens (drafts
    accepted in the member-vmapped multi-token forward) while the output
    stays the plain stacked engine's greedy continuation (up to the
    documented argmax near-ties between program shapes)."""
    from tests.test_spec_decode import _assert_same_or_tie_flip

    spec = resolve_spec("llama-tiny", {"max_seq": "128"})
    plain = InferenceEngine(spec, seed=0, members=2, decode_chunk=4, n_slots=1)
    fast = InferenceEngine(spec, seed=0, members=2, decode_chunk=4, n_slots=1,
                           spec_decode=4)
    prompt = [9, 8, 9, 8, 9, 8, 9, 8]
    greedy = SamplerConfig(temperature=0.0)
    refs = {m: plain.generate(prompt, member=m, max_new_tokens=12,
                              sampler=greedy).token_ids for m in range(2)}
    # Oracle drafts (the sibling test's pattern): propose each member's own
    # greedy continuation so the verify path deterministically engages —
    # prompt-lookup hits depend on the random weights' output repeating.
    fast._draft = lambda req, g: (
        refs[req.member][req.emitted: req.emitted + g]
        if req.emitted + g <= len(refs[req.member]) else None)
    # Pin verify-path ENGAGEMENT, not just output equality: without this, a
    # regression that silently falls back to the plain chunked path would
    # keep the test green while the feature is dead.
    verifies = {"n": 0}
    real = fast._verify_fn

    def counting(*args, **kwargs):
        fn = real(*args, **kwargs)

        def wrapped(*a, **k):
            verifies["n"] += 1
            return fn(*a, **k)
        return wrapped

    fast._verify_fn = counting
    for m in range(2):
        b = fast.generate(prompt, member=m, max_new_tokens=12,
                          sampler=greedy).token_ids
        assert len(b) == 12
        # near-tie audit needs member m's own weights (seed == m here)
        _assert_same_or_tie_flip(prompt, refs[m], b, member_seed=m)
    assert verifies["n"] >= 1, "speculative verify path never engaged"


def test_shared_stacked_engine_spec_decode_merge():
    """The cached-engine merge honors a later backend's spec_decode= knob on
    stacked engines too (the verify program is member-vmapped)."""
    spec = resolve_spec("llama-tiny", {"max_seq": "64"})
    first = get_engine(spec, seed=400, members=2, n_slots=1)
    assert first.spec_decode == 0
    again = get_engine(spec, seed=400, members=2, n_slots=1, spec_decode=4)
    assert again is first and first.spec_decode == 4


def test_backend_urls_share_one_engine():
    """members=M&member=i backends resolve to ONE engine; distinct member
    indices; rejected for ckpt backends and out-of-range members."""
    def mk(i):
        return TpuBackend.from_spec(BackendSpec(
            name=f"LLM{i}",
            url=f"tpu://llama-tiny?members={M}&member={i}&slots=2",
            model="llama-tiny",
        ))

    backends = [mk(i) for i in range(M)]
    assert len({id(b.engine) for b in backends}) == 1
    assert [b.member for b in backends] == list(range(M))
    assert backends[0].engine.members == M

    with pytest.raises(ValueError, match="out of range"):
        TpuBackend.from_spec(BackendSpec(
            name="bad", url=f"tpu://llama-tiny?members={M}&member={M}",
            model="x"))
    with pytest.raises(ValueError, match="does not apply to ckpt"):
        TpuBackend.from_spec(BackendSpec(
            name="bad", url="tpu://llama-tiny?members=2&ckpt=/tmp/nope",
            model="x"))


async def test_stacked_quorum_through_real_socket():
    """The shipped stacked shape end-to-end: a members=3 quorum served by
    the bundled h11 server over TCP streams per-member `chatcmpl-parallel-i`
    deltas and a final combined chunk whose sections are the three members'
    streams (the /verify scenario, pinned)."""
    import httpx

    from quorum_tpu.config import Config
    from quorum_tpu.server.app import create_app
    from quorum_tpu.server.serve import start_server
    from tests.conftest import ParallelStreamCollector

    config = Config(raw={
        "settings": {"timeout": 120},
        "primary_backends": [
            {"name": f"LLM{i}",
             "url": f"tpu://llama-tiny?members=3&member={i}&slots=2",
             "model": "tiny"}
            for i in range(3)
        ],
        "iterations": {"aggregation": {"strategy": "concatenate"}},
        "strategy": {"concatenate": {
            "separator": "\n---\n",
            "hide_intermediate_think": False,
            "hide_final_think": False,
            "thinking_tags": ["think"],
        }},
    })
    server = await start_server(create_app(config), "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    col = ParallelStreamCollector()
    try:
        async with httpx.AsyncClient(
            base_url=f"http://127.0.0.1:{port}", timeout=120
        ) as client:
            async with client.stream(
                "POST", "/chat/completions",
                json={"model": "tiny", "stream": True, "max_tokens": 5,
                      "temperature": 0.8, "seed": 6,
                      "messages": [{"role": "user", "content": "hi"}]},
                headers={"Authorization": "Bearer t"},
            ) as resp:
                assert resp.status_code == 200
                async for line in resp.aiter_lines():
                    col.feed_line(line)
    finally:
        server.close()
        await server.wait_closed()
    assert sorted(col.texts) == [0, 1, 2], "all three members streamed"
    streams = [col.stream(i) for i in range(3)]
    assert "".join(col.final) == "\n---\n".join(streams)


def test_stacked_engine_matches_separate_seeded_engines_via_backend():
    """End-to-end: the stacked backends' completions equal the old
    three-separate-engines completions (seed i ↔ member i)."""
    import asyncio

    def complete(backend, body):
        return asyncio.run(backend.complete(dict(body), {}, timeout=60))

    body = {
        "model": "m",
        "messages": [{"role": "user", "content": "hello quorum"}],
        "max_tokens": 6,
        "temperature": 0.8,
        "seed": 2,
    }
    stacked = [
        TpuBackend.from_spec(BackendSpec(
            name=f"S{i}",
            url=f"tpu://llama-tiny?members={M}&member={i}&slots=2",
            model="m"))
        for i in range(M)
    ]
    singles = [
        TpuBackend.from_spec(BackendSpec(
            name=f"P{i}", url=f"tpu://llama-tiny?seed={i}&slots=2",
            model="m"))
        for i in range(M)
    ]
    got = [complete(b, body).body["choices"][0]["message"]["content"]
           for b in stacked]
    want = [complete(b, body).body["choices"][0]["message"]["content"]
            for b in singles]
    assert got == want
