"""/metrics endpoint: Prometheus exposition of engine scheduler state
(SURVEY §5.5 — the reference exports no metrics at all)."""

from tests.conftest import make_client


def _config():
    return {
        "settings": {"timeout": 60},
        "primary_backends": [
            {"name": "LLM1", "url": "tpu://llama-tiny?seed=9107&slots=2", "model": "t"},
        ],
    }


async def test_metrics_exposition():
    async with make_client(_config()) as client:
        before = (await client.get("/metrics")).text
        assert "quorum_tpu_uptime_seconds" in before
        assert 'quorum_tpu_engine_slots{backend="LLM1"} 2' in before
        assert 'quorum_tpu_engine_requests_total{backend="LLM1"} 0' in before
        # members is exported as a gauge (1 on ordinary engines; M on
        # stacked engines, whose "slots" reads M x n_slots flat rows)
        assert 'quorum_tpu_engine_members{backend="LLM1"} 1' in before
        assert "# TYPE quorum_tpu_engine_members gauge" in before
        # round-3 counters, typed as counters in the exposition
        for key in ("cancellations_total", "spec_turns_total",
                    "spec_accepted_total"):
            assert f"# TYPE quorum_tpu_engine_{key} counter" in before
            assert f'quorum_tpu_engine_{key}{{backend="LLM1"}} 0' in before

        resp = await client.post(
            "/v1/chat/completions",
            json={"model": "t", "messages": [{"role": "user", "content": "hi"}],
                  "max_tokens": 5},
            headers={"Authorization": "Bearer x"},
        )
        assert resp.status_code == 200

        after = (await client.get("/v1/metrics")).text
        assert 'quorum_tpu_engine_requests_total{backend="LLM1"} 1' in after
        assert 'quorum_tpu_engine_tokens_total{backend="LLM1"} 5' in after
        assert 'quorum_tpu_engine_busy_slots{backend="LLM1"} 0' in after
        assert 'quorum_tpu_engine_failures_total{backend="LLM1"} 0' in after
        # prometheus text format: TYPE comments present
        assert "# TYPE quorum_tpu_engine_tokens_total counter" in after
        # step-loop occupancy counters (ISSUE 1): decode dispatch turns and
        # the busy-row sum they stepped
        assert "# TYPE quorum_tpu_engine_decode_chunks_total counter" in after
        assert ("# TYPE quorum_tpu_engine_decode_busy_rows_total counter"
                in after)
        # latency histogram families with full exposition triplets
        for fam in ("quorum_tpu_request_duration_seconds",
                    "quorum_tpu_ttft_seconds",
                    "quorum_tpu_inter_token_seconds",
                    "quorum_tpu_queue_wait_seconds"):
            assert f"# TYPE {fam} histogram" in after, fam
            assert f"{fam}_sum" in after, fam
            assert f"{fam}_count" in after, fam
        # request duration carries a status-class label so error floods
        # don't read as latency improvements
        assert ('quorum_tpu_request_duration_seconds_bucket'
                '{status="2xx",le="+Inf"}') in after
        assert 'quorum_tpu_queue_wait_seconds_bucket{le="+Inf"}' in after
