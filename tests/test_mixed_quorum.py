"""Heterogeneous-model quorum: three DIFFERENT architectures co-located.

BASELINE.md benchmark config 3 is a mixed fan-out (Llama-3-8B + Mistral-7B
+ Gemma-7B, concatenate). This pins that shape end-to-end at tiny scale:
three ``tpu://`` backends of three distinct model families (llama GQA+RMS,
mixtral sparse-MoE, gemma geglu+emb-scale) serve one request through the
app — three engines with different compiled programs co-resident on one
device, fanned out and concatenated — in both non-streaming and SSE modes.
"""

import httpx

from quorum_tpu.config import Config
from quorum_tpu.server.app import create_app

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

SEP = "\n=====\n"


def mixed_client() -> httpx.AsyncClient:
    urls = [
        ("LLAMA", "tpu://llama-tiny?seed=1&slots=2&max_tokens=8"),
        ("MIXTRAL", "tpu://mixtral-tiny?seed=2&slots=2&max_tokens=8"),
        ("GEMMA", "tpu://gemma-tiny?seed=3&slots=2&max_tokens=8"),
    ]
    config = Config(raw={
        "settings": {"timeout": 120},
        "primary_backends": [
            {"name": n, "url": u, "model": n.lower()} for n, u in urls
        ],
        "iterations": {"aggregation": {"strategy": "concatenate"}},
        "strategy": {
            "concatenate": {
                "separator": SEP,
                "hide_intermediate_think": False,
                "hide_final_think": False,
                "thinking_tags": ["think"],
            },
        },
    })
    transport = httpx.ASGITransport(app=create_app(config))
    return httpx.AsyncClient(
        transport=transport, base_url="http://testserver",
        headers={"Authorization": "Bearer t"}, timeout=300,
    )


BODY = {
    "model": "quorum",
    "messages": [{"role": "user", "content": "mixed families, one chip"}],
    "max_tokens": 6,
    "temperature": 0.8,
    "seed": 11,
}


async def test_mixed_family_quorum_non_streaming():
    async with mixed_client() as client:
        resp = await client.post("/chat/completions", json=BODY)
    assert resp.status_code == 200, resp.text[:300]
    body = resp.json()
    parts = body["choices"][0]["message"]["content"].split(SEP)
    assert len(parts) == 3, "one section per model family"
    assert all(p for p in parts), "every family produced text"
    # three distinct architectures with distinct weights — identical outputs
    # would mean a routing bug, not a coincidence
    assert len(set(parts)) == 3
    # usage sums real engine counts across the three families
    assert body["usage"]["completion_tokens"] == 18


async def test_mixed_family_quorum_streaming():
    from tests.conftest import ParallelStreamCollector

    col = ParallelStreamCollector()
    async with mixed_client() as client:
        async with client.stream(
            "POST", "/chat/completions", json=BODY | {"stream": True}
        ) as resp:
            assert resp.status_code == 200
            async for line in resp.aiter_lines():
                col.feed_line(line)
    assert sorted(col.texts) == [0, 1, 2], "all three families streamed"
    streams = [col.stream(i) for i in range(3)]
    assert len(set(streams)) == 3
