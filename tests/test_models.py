"""Model-runtime unit tests: all three families, cache consistency, sampling.

Strategy per SURVEY.md §4(c): TPU-free jax-on-CPU with tiny presets — the
same code paths the TPU runs, at toy sizes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quorum_tpu.models import (
    MODEL_PRESETS,
    decode_step,
    forward_logits,
    init_params,
    prefill,
    resolve_spec,
)
from quorum_tpu.models.init import param_count
from quorum_tpu.models.transformer import init_cache
from quorum_tpu.ops.sampling import SamplerConfig, sample_token

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

TINY = ["gpt2-tiny", "llama-tiny", "mixtral-tiny", "gemma-tiny"]


def _toy_batch():
    toks = jnp.array([[5, 6, 7, 8, 0, 0], [9, 10, 0, 0, 0, 0]], dtype=jnp.int32)
    lengths = jnp.array([4, 2], dtype=jnp.int32)
    return toks, lengths


@pytest.mark.parametrize("model_id", TINY)
def test_prefill_matches_cache_free_forward(model_id):
    spec = resolve_spec(model_id)
    params = init_params(spec, seed=0)
    toks, lengths = _toy_batch()
    ck, cv = init_cache(spec, 2)
    logits, ck, cv = jax.jit(prefill, static_argnums=(1,))(
        params, spec, toks, lengths, ck, cv
    )
    full = jax.jit(forward_logits, static_argnums=(1,))(params, spec, toks)
    np.testing.assert_allclose(
        np.asarray(logits[0]), np.asarray(full[0, 3]), rtol=2e-2, atol=2e-2
    )
    np.testing.assert_allclose(
        np.asarray(logits[1]), np.asarray(full[1, 1]), rtol=2e-2, atol=2e-2
    )


@pytest.mark.parametrize("model_id", TINY)
def test_decode_step_matches_extended_forward(model_id):
    spec = resolve_spec(model_id)
    params = init_params(spec, seed=0)
    toks, lengths = _toy_batch()
    ck, cv = init_cache(spec, 2)
    logits, ck, cv = jax.jit(prefill, static_argnums=(1,))(
        params, spec, toks, lengths, ck, cv
    )
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    dl, ck, cv = jax.jit(decode_step, static_argnums=(1,))(
        params, spec, nxt, lengths, ck, cv
    )
    toks2 = toks.at[0, 4].set(nxt[0]).at[1, 2].set(nxt[1])
    full2 = jax.jit(forward_logits, static_argnums=(1,))(params, spec, toks2)
    np.testing.assert_allclose(
        np.asarray(dl[0]), np.asarray(full2[0, 4]), rtol=2e-2, atol=2e-2
    )
    np.testing.assert_allclose(
        np.asarray(dl[1]), np.asarray(full2[1, 2]), rtol=2e-2, atol=2e-2
    )


def test_multi_step_greedy_decode_is_deterministic():
    spec = resolve_spec("llama-tiny")
    params = init_params(spec, seed=0)
    toks = jnp.array([[3, 1, 4, 1, 5]], dtype=jnp.int32)
    lengths = jnp.array([5], dtype=jnp.int32)

    def run():
        ck, cv = init_cache(spec, 1)
        logits, ck, cv = jax.jit(prefill, static_argnums=(1,))(
            params, spec, toks, lengths, ck, cv
        )
        out, ls = [], lengths
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        step = jax.jit(decode_step, static_argnums=(1,))
        for _ in range(8):
            out.append(int(tok[0]))
            logits, ck, cv = step(params, spec, tok, ls, ck, cv)
            ls = ls + 1
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
        return out

    assert run() == run()


def test_padding_does_not_change_results():
    """Right-padding the prompt bucket must not affect logits (static shapes)."""
    spec = resolve_spec("llama-tiny")
    params = init_params(spec, seed=0)
    lengths = jnp.array([3], dtype=jnp.int32)
    short = jnp.array([[7, 8, 9]], dtype=jnp.int32)
    padded = jnp.array([[7, 8, 9, 0, 0, 0, 0, 0]], dtype=jnp.int32)
    ck, cv = init_cache(spec, 1)
    l1, *_ = jax.jit(prefill, static_argnums=(1,))(params, spec, short, lengths, ck, cv)
    ck, cv = init_cache(spec, 1)
    l2, *_ = jax.jit(prefill, static_argnums=(1,))(params, spec, padded, lengths, ck, cv)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), rtol=2e-2, atol=2e-2)


def test_presets_resolve_and_validate():
    for name in MODEL_PRESETS:
        spec = resolve_spec(name)
        assert spec.validate() is spec


def test_resolve_spec_query_overrides():
    spec = resolve_spec("llama-tiny", {"n_layers": "3", "rope_theta": "500000.0", "tp": "4"})
    assert spec.n_layers == 3
    assert spec.rope_theta == 500000.0  # engine option "tp" ignored here


def test_resolve_spec_unknown_id_raises():
    with pytest.raises(KeyError):
        resolve_spec("no-such-model")


def test_gpt2_preset_param_count_is_124m():
    params = init_params(resolve_spec("gpt2"), seed=0)
    n = param_count(params)
    assert 120e6 < n < 130e6, n


def test_sampling_greedy_and_topk():
    logits = jnp.array([[0.0, 5.0, 1.0, 2.0]])
    key = jax.random.PRNGKey(0)
    assert int(sample_token(logits, key, SamplerConfig(temperature=0.0))[0]) == 1
    # top_k=1 at any temperature must also pick the argmax
    assert int(sample_token(logits, key, SamplerConfig(temperature=2.0, top_k=1))[0]) == 1
    # top_p tiny → only the argmax survives the nucleus
    assert int(sample_token(logits, key, SamplerConfig(temperature=1.0, top_p=0.1))[0]) == 1


def test_sampling_temperature_distribution():
    logits = jnp.zeros((1, 4)).at[0, 2].set(3.0)
    keys = jax.random.split(jax.random.PRNGKey(1), 64)
    toks = [int(sample_token(logits, k, SamplerConfig(temperature=1.0))[0]) for k in keys]
    assert max(set(toks), key=toks.count) == 2
    assert len(set(toks)) > 1  # not greedy


def test_llama31_scaled_rope_preset_serves_and_scaling_is_load_bearing():
    """The llama-3.1 preset (rope_scaling=llama3), tiny-ified via URL
    overrides, serves through the engine; and the scaled tables really
    differ from plain RoPE in the stretched band."""
    import numpy as np

    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.ops.rotary import rope_cos_sin, rope_cos_sin_for

    tiny = {"n_layers": "2", "d_model": "64", "n_heads": "4",
            "n_kv_heads": "2", "head_dim": "16", "d_ff": "128",
            "vocab_size": "512", "max_seq": "128",
            "rope_original_max_seq": "32"}
    spec = resolve_spec("llama-3.1-8b", tiny)
    assert spec.rope_scaling == "llama3"
    eng = InferenceEngine(spec, decode_chunk=4, n_slots=1)
    out = eng.generate([3, 4, 5, 6], max_new_tokens=6,
                       sampler=SamplerConfig(temperature=0.0),
                       seed=0).token_ids
    eng.shutdown()
    assert len(out) == 6

    cos_s, _ = rope_cos_sin_for(spec)
    cos_p, _ = rope_cos_sin(spec.max_seq, spec.head_dim, spec.rope_theta)
    # Low-frequency (long-wavelength) components are stretched by the
    # factor; the highest-frequency component is untouched.
    assert float(np.abs(np.asarray(cos_s) - np.asarray(cos_p)).max()) > 0.1
    np.testing.assert_allclose(np.asarray(cos_s[:, 0]),
                               np.asarray(cos_p[:, 0]), atol=1e-6)
