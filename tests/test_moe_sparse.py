"""Grouped sparse-MoE compute vs the dense oracle (VERDICT r2 weakness 4).

The grouped path dispatches tokens to fixed-capacity expert buffers and runs
only the selected experts' matmuls; with capacity_factor ≥ E/k no pick can
drop, so its output must match the dense all-experts path numerically.
"""

import jax
import jax.numpy as jnp
import numpy as np

from quorum_tpu.models import init_params, resolve_spec
from quorum_tpu.models.transformer import (
    _moe_mlp_dense,
    _moe_mlp_grouped,
    forward_logits,
)
from quorum_tpu.parallel import MeshConfig, make_mesh, shard_pytree

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

SPEC = resolve_spec("mixtral-tiny")  # E=4, k=2, cf=2.0 → no drops


def _layer0_block(params):
    return jax.tree.map(
        lambda v: v[0] if v is not None else None,
        params["blocks"],
        is_leaf=lambda v: v is None or hasattr(v, "shape"),
    )


def test_grouped_matches_dense_oracle():
    params = init_params(SPEC, seed=0)
    block = _layer0_block(params)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 24, SPEC.d_model),
                          jnp.float32).astype(jnp.bfloat16)
    dense = np.asarray(_moe_mlp_dense(x, block, SPEC), np.float32)
    grouped = np.asarray(_moe_mlp_grouped(x, block, SPEC), np.float32)
    np.testing.assert_allclose(grouped, dense, rtol=5e-2, atol=5e-2)
    # the outputs are genuinely nonzero (the gather/scatter isn't a no-op)
    assert np.abs(dense).max() > 1e-3


def test_grouped_capacity_drops_overflow_only():
    """With a tight capacity (cf such that C < N), overflow picks drop but
    every surviving token still matches the oracle's routing weights
    direction: the output stays finite and within the oracle's envelope."""
    import dataclasses

    tight = dataclasses.replace(SPEC, moe_capacity_factor=0.5)
    params = init_params(tight, seed=0)
    block = _layer0_block(params)
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 32, tight.d_model),
                          jnp.float32).astype(jnp.bfloat16)
    out = np.asarray(_moe_mlp_grouped(x, block, tight), np.float32)
    assert np.isfinite(out).all()
    # capacity 0.5·k·N/E = 8 rows per expert < N=32: some picks must drop,
    # so the tight output differs from the full-capacity one.
    full = np.asarray(_moe_mlp_grouped(x, block, SPEC), np.float32)
    assert not np.allclose(out, full)


def test_full_model_prefill_uses_grouped_and_matches():
    """forward_logits (T>1 → grouped MoE) must stay consistent with itself
    under tp/ep sharding on the 8-device mesh."""
    params = init_params(SPEC, seed=0)
    toks = jnp.array([[5, 6, 7, 8, 9, 10, 11, 12]], jnp.int32)
    single = np.asarray(
        jax.jit(lambda p, t: forward_logits(p, SPEC, t))(params, toks),
        np.float32,
    )
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    sharded_params = shard_pytree(mesh, params)
    sharded = np.asarray(
        jax.jit(lambda p, t: forward_logits(p, SPEC, t))(sharded_params, toks),
        np.float32,
    )
    np.testing.assert_allclose(sharded, single, rtol=2e-2, atol=2e-2)


def test_moe_engine_generation_still_consistent():
    """End-to-end: a MoE engine (grouped prefill, dense decode) generates
    identically whether the prompt is admitted single-shot or chunked —
    i.e. the grouped prefill writes the same KV state."""
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.ops.sampling import SamplerConfig

    prompt = [(11 + 7 * i) % 500 for i in range(48)]
    eng_one = InferenceEngine(SPEC, n_slots=2, prefill_chunk=0)
    eng_seg = InferenceEngine(SPEC, n_slots=2, prefill_chunk=16)
    one = eng_one.generate(prompt, max_new_tokens=8,
                           sampler=SamplerConfig(temperature=0.0)).token_ids
    seg = eng_seg.generate(prompt, max_new_tokens=8,
                           sampler=SamplerConfig(temperature=0.0)).token_ids
    assert one == seg
    assert len(one) == 8
