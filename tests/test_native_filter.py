"""Native C++ thinking-tag filter: byte-exact equivalence with the Python
reference implementation (which encodes the reference proxy's semantics,
tests/test_filtering.py)."""

import random

import pytest

from quorum_tpu.filtering import DEFAULT_THINKING_TAGS, ThinkingTagFilter
from quorum_tpu.native import (
    NativeThinkingTagFilter,
    make_thinking_filter,
    native_available,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain in this environment"
)

TAGS = list(DEFAULT_THINKING_TAGS)

CASES = [
    ["plain text, no tags"],
    ["before <think>hidden</think> after"],
    ["a<think>b</think>c<reason>d</reason>e"],
    ["split <thi", "nk>hidden</think> visible"],
    ["open <think>h", "idden</th", "ink> done"],
    ["nested <think>a<think>b</think>c</think>d"],
    ["<THINK>case</THINK>ok"],
    ["stray close</think> passes through"],
    ["unterminated <think>never closed"],
    ["trailing partial <thi"],
    ["< not a tag <th!nk> also not"],
    ["<think></think>empty"],
    ["a<reasoning>x</reasoning>b<thought>y</thought>c"],
    ["multi\nline <think>hid\nden</think> text\n"],
    ["unicode ✓ <think>héllo</think> wörld"],
]


def run_pair(chunks, tags=TAGS):
    py = ThinkingTagFilter(tags)
    cc = NativeThinkingTagFilter(tags)
    py_out = [py.feed(c) for c in chunks] + [py.flush()]
    cc_out = [cc.feed(c) for c in chunks] + [cc.flush()]
    return py_out, cc_out


@pytest.mark.parametrize("chunks", CASES, ids=[c[0][:28] for c in CASES])
def test_native_matches_python(chunks):
    py_out, cc_out = run_pair(chunks)
    assert cc_out == py_out


def test_native_matches_python_fuzz():
    """Randomized corpus re-chunked at random boundaries: every feed() must
    return byte-identical output to the Python reference."""
    rng = random.Random(42)
    alphabet = ["<", ">", "/", "think", "reason", "t", "x ", "<think>",
                "</think>", "<reasoning>", "</reasoning>", "✓", "\n"]
    for _ in range(200):
        text = "".join(rng.choice(alphabet) for _ in range(rng.randint(1, 60)))
        chunks, i = [], 0
        while i < len(text):
            j = min(len(text), i + rng.randint(1, 7))
            chunks.append(text[i:j])
            i = j
        py_out, cc_out = run_pair(chunks)
        assert cc_out == py_out, (text, chunks, py_out, cc_out)


def test_no_tags_passthrough():
    py_out, cc_out = run_pair(["anything <think> goes"], tags=[])
    assert cc_out == py_out
    assert cc_out[0] == "anything <think> goes"


def test_make_thinking_filter_defaults_to_python(monkeypatch):
    """Python is the measured-faster default at SSE-delta granularity."""
    monkeypatch.delenv("QUORUM_TPU_NATIVE", raising=False)
    f = make_thinking_filter(TAGS)
    assert isinstance(f, ThinkingTagFilter)


def test_make_thinking_filter_native_opt_in(monkeypatch):
    monkeypatch.setenv("QUORUM_TPU_NATIVE", "1")
    f = make_thinking_filter(TAGS)
    assert isinstance(f, NativeThinkingTagFilter)
