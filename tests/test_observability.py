"""Observability: aggregation log file channel, request ids, phase timing.

Reference behavior being mirrored: dedicated ``aggregation`` logger writing
``logs/aggregation.log`` with a startup test write
(/root/reference/src/quorum/oai_proxy.py:17-37)."""

import logging

from tests.conftest import make_client, two_backend_parallel_config

from quorum_tpu.backends.fake import FakeBackend
from quorum_tpu.observability import PhaseTimer, setup_aggregation_log


def test_setup_aggregation_log_writes_file(tmp_path):
    path = setup_aggregation_log(tmp_path / "logs")
    assert path.exists()
    assert "Aggregation logging initialized" in path.read_text()
    # idempotent: second call must not duplicate handlers
    n = len(logging.getLogger("aggregation").handlers)
    setup_aggregation_log(tmp_path / "logs")
    assert len(logging.getLogger("aggregation").handlers) == n


def test_phase_timer_accumulates():
    t = PhaseTimer("req-x")
    with t.phase("fanout"):
        pass
    with t.phase("fanout"):
        pass
    with t.phase("combine"):
        pass
    assert set(t.phases) == {"fanout", "combine"}
    assert t.total >= t.phases["fanout"]
    t.log("complete", status=200)  # must not raise


async def test_response_carries_request_id():
    cfg = two_backend_parallel_config()
    client = make_client(
        cfg,
        LLM1=FakeBackend("LLM1", text="a"),
        LLM2=FakeBackend("LLM2", text="b"),
    )
    r = await client.post(
        "/chat/completions",
        json={"model": "m", "messages": [{"role": "user", "content": "q"}]},
        headers={"Authorization": "Bearer k"},
    )
    assert r.status_code == 200
    assert r.headers["x-request-id"].startswith("req-")


def test_setup_aggregation_log_honors_new_directory(tmp_path):
    """A later call with a different dir must attach a handler there, not
    silently keep logging only to the first location."""
    p1 = setup_aggregation_log(tmp_path / "a")
    p2 = setup_aggregation_log(tmp_path / "b")
    assert p1 != p2
    assert p2.exists()
    logging.getLogger("aggregation").info("hello-both")
    assert "hello-both" in p1.read_text()
    assert "hello-both" in p2.read_text()


async def test_max_tokens_zero_rejected_400():
    from quorum_tpu.backends.base import BackendError
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec
    import pytest

    b = TpuBackend.from_spec(BackendSpec(name="T", url="tpu://llama-tiny"))
    with pytest.raises(BackendError) as ei:
        await b.complete(
            {"messages": [{"role": "user", "content": "x"}], "max_tokens": 0}, {}, 30.0
        )
    assert ei.value.status_code == 400
