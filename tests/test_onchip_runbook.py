"""Crash-safety helpers of the on-chip runbook (scripts/onchip_session.py).

The runbook exists because the TPU tunnel dies mid-session; its banking
must therefore survive exactly that: partial writes, corrupt files from a
mid-write kill, and children whose stdout ends mid-line.
"""

import importlib.util
import json
import os
import sys

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def _load():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "onchip_session.py")
    spec = importlib.util.spec_from_file_location("onchip_session", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bank_merges_and_survives_corruption(tmp_path, monkeypatch):
    mod = _load()
    out = tmp_path / "ONCHIP.json"
    monkeypatch.setattr(mod, "OUT", str(out))

    mod.bank({"a": 1})
    mod.bank({"b": 2.5})
    assert json.loads(out.read_text()) == {"a": 1, "b": 2.5}

    # A mid-write kill leaves a truncated file; the next bank must recover
    # (start fresh) instead of crashing every later session.
    out.write_text('{"a": 1, "b"')
    mod.bank({"c": 3})
    assert json.loads(out.read_text()) == {"c": 3}
    # no stray temp file left behind
    assert not (tmp_path / "ONCHIP.json.tmp").exists()


def test_quant_quality_step_end_to_end(monkeypatch):
    """The int8-quality step runs both precision arms for real (tiny model
    on CPU) and reports the delta/ppl summary with a sane shape."""
    mod = _load()
    monkeypatch.setenv("QUORUM_TPU_QQ_MODEL", "llama-tiny")
    monkeypatch.setattr(mod, "probe_with_retry", lambda *a, **k: True)
    got = mod.quant_quality_step()
    assert got.get("qq_model") == "llama-tiny", got
    assert got["qq_n_scored_tokens"] == 511
    assert got["qq_mean_abs_dlogprob"] >= 0.0
    assert got["qq_ppl_bf16"] > 0 and got["qq_ppl_int8"] > 0
    # int8 of the same weights is a small perturbation, not a different
    # model: ppl within a factor of 2 either way on the tiny proxy.
    assert 0.5 < got["qq_ppl_ratio"] < 2.0, got


def test_session_budget_exhaustion_skips_cleanly(tmp_path, monkeypatch):
    """A supervisor-trimmed budget (QUORUM_TPU_ONCHIP_BUDGET) that cannot
    fit any step makes the session bank explicit skip markers and exit
    cleanly — never a mid-computation kill of the TPU holder."""
    mod = _load()
    out = tmp_path / "ONCHIP.json"
    monkeypatch.setattr(mod, "OUT", str(out))
    monkeypatch.setattr(mod, "probe_with_retry", lambda *a, **k: True)
    monkeypatch.setenv("QUORUM_TPU_ONCHIP_BUDGET", "1")
    calls = []
    monkeypatch.setattr(mod, "run_step",
                        lambda *a, **k: calls.append(a) or {"x": 1})
    monkeypatch.setattr(mod.sys, "argv", ["onchip_session.py"])
    mod.main()
    assert calls == [], "no step may launch with an exhausted budget"
    banked = json.loads(out.read_text())
    for step in ("bench", "ab", "kvq", "flash_off", "flash_on",
                 "loop_off", "loop_on", "spec_off", "spec_on",
                 "zero_drain_off", "zero_drain_on", "qq",
                 "profile"):
        assert banked.get(f"{step}_error") == (
            "skipped: session budget exhausted"), (step, banked)


def test_kill_process_tree_reaches_own_session_grandchildren():
    """The kill discipline must reach a grandchild running in its OWN
    session (run_step starts step children with start_new_session=True) —
    killpg on the parent's group alone orphans exactly the process that
    holds the single-holder TPU client."""
    import subprocess
    import sys
    import time

    mod = _load()
    parent = subprocess.Popen([sys.executable, "-c", (
        "import subprocess, sys, time\n"
        "subprocess.Popen([sys.executable, '-c',"
        " 'import time; time.sleep(600)'], start_new_session=True)\n"
        "time.sleep(600)\n")], start_new_session=True)
    gchildren = []
    for _ in range(30):
        time.sleep(0.5)
        out = subprocess.run(["ps", "-eo", "pid,ppid"],
                             capture_output=True, text=True).stdout
        rows = [ln.split() for ln in out.splitlines()[1:]
                if len(ln.split()) == 2]
        gchildren = [int(p) for p, pp in rows
                     if pp.isdigit() and int(pp) == parent.pid]
        if gchildren:
            break
    assert gchildren, "test harness never saw the grandchild"
    mod.kill_process_tree(parent.pid)
    parent.wait()
    time.sleep(0.5)
    for g in gchildren:
        try:
            with open(f"/proc/{g}/stat") as f:
                state = f.read().rsplit(")", 1)[-1].split()[0]
            assert state == "Z", f"grandchild {g} alive in state {state}"
        except (ProcessLookupError, OSError):
            pass  # already reaped — dead is dead


def test_last_json_salvages_checkpoint_line():
    mod = _load()
    # A timed-out child's stdout can end mid-line; the intact checkpoint
    # line above it must be salvaged.
    stdout = 'noise\n{"good": 1}\n{"partial": '
    assert mod._last_json(stdout) == {"good": 1}
    assert mod._last_json("") == {}
    assert mod._last_json(None) == {}


def test_full_session_rehearsal_on_cpu(tmp_path, monkeypatch):
    """Dress rehearsal of the WHOLE runbook (main(), every step) against
    tiny models on CPU: a live tunnel window is too precious to be the
    first time scripts/onchip_session.py executes end-to-end. Probes are
    stubbed alive; everything else — subprocess plumbing, process-group
    kill discipline wiring, banked-key schema per step — runs for real."""
    mod = _load()
    out = tmp_path / "ONCHIP.json"
    monkeypatch.setattr(mod, "OUT", str(out))
    monkeypatch.setattr(mod, "probe_with_retry", lambda *a, **k: True)
    # Tiny analogs of the real step URLs (same knob set, CPU-sized):
    monkeypatch.setattr(mod, "KVQ_URL", (
        "tpu://llama-tiny?max_seq=2048&slots=2&decode_chunk=8"
        "&max_tokens=16&quant=int8&kv_quant=int8&prefill_chunk=256"))
    monkeypatch.setattr(mod, "B7_URL", (
        "tpu://llama-tiny?max_seq=4096&slots=2&decode_chunk=8"
        "&max_tokens=16&prefill_chunk=256"))
    # The bench and qq children read these from the inherited env:
    for k, v in (("QUORUM_TPU_QQ_MODEL", "llama-tiny"),
                 ("QUORUM_TPU_BENCH_MODEL", "gpt2-tiny"),
                 ("QUORUM_TPU_BENCH_TTFT_REQUESTS", "2"),
                 ("QUORUM_TPU_BENCH_THROUGHPUT_REQUESTS", "4"),
                 ("QUORUM_TPU_BENCH_MAX_TOKENS", "8"),
                 ("QUORUM_TPU_BENCH_7B", "0"),
                 ("QUORUM_TPU_BENCH_7B_QUANT", "0"),
                 ("QUORUM_TPU_BENCH_CKPT", "0")):
        monkeypatch.setenv(k, v)
    monkeypatch.delenv("QUORUM_TPU_ONCHIP_BUDGET", raising=False)
    monkeypatch.setattr(sys, "argv", ["onchip_session.py"])
    mod.main()

    banked = json.loads(out.read_text())
    # Every step banked its keys; none banked an error.
    errors = {k: v for k, v in banked.items()
              if k.endswith("_error") and v}
    assert not errors, errors
    assert banked["value"] > 0  # bench headline (phase 1/2) landed
    assert banked["tokens_per_s"] > 0
    assert any(k.startswith("ab_p50") for k in banked), sorted(banked)
    assert banked["kvq_decode_tok_s"] > 0
    assert banked["flash_off_agg_decode_tok_s"] > 0
    assert banked["flash_on_agg_decode_tok_s"] > 0
    # megachunk A/B (decode_loop=4 vs unfused) banked both arms
    assert banked["loop_off_decode_tok_s"] > 0
    assert banked["loop_on_decode_tok_s"] > 0
    assert banked["qq_model"] == "llama-tiny"
    assert 0.5 < banked["qq_ppl_ratio"] < 2.0
    assert banked["profile_ttft_ms"] > 0
    assert banked.get("profile_artifacts", 0) >= 0
