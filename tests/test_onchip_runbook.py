"""Crash-safety helpers of the on-chip runbook (scripts/onchip_session.py).

The runbook exists because the TPU tunnel dies mid-session; its banking
must therefore survive exactly that: partial writes, corrupt files from a
mid-write kill, and children whose stdout ends mid-line.
"""

import importlib.util
import json
import os

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def _load():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "onchip_session.py")
    spec = importlib.util.spec_from_file_location("onchip_session", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bank_merges_and_survives_corruption(tmp_path, monkeypatch):
    mod = _load()
    out = tmp_path / "ONCHIP.json"
    monkeypatch.setattr(mod, "OUT", str(out))

    mod.bank({"a": 1})
    mod.bank({"b": 2.5})
    assert json.loads(out.read_text()) == {"a": 1, "b": 2.5}

    # A mid-write kill leaves a truncated file; the next bank must recover
    # (start fresh) instead of crashing every later session.
    out.write_text('{"a": 1, "b"')
    mod.bank({"c": 3})
    assert json.loads(out.read_text()) == {"c": 3}
    # no stray temp file left behind
    assert not (tmp_path / "ONCHIP.json.tmp").exists()


def test_last_json_salvages_checkpoint_line():
    mod = _load()
    # A timed-out child's stdout can end mid-line; the intact checkpoint
    # line above it must be salvaged.
    stdout = 'noise\n{"good": 1}\n{"partial": '
    assert mod._last_json(stdout) == {"good": 1}
    assert mod._last_json("") == {}
    assert mod._last_json(None) == {}
