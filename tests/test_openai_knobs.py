"""The OpenAI request-knob contract on tpu:// backends (docs/api.md table;
VERDICT r2 missing item 1 — the round-2 backend silently ignored these).

Every knob has an accept test (it changes/structures the output as
documented) and a reject test (out-of-range or unsupported values are a 400,
not a silent ignore or a 500).
"""

import asyncio

import numpy as np
import pytest

from quorum_tpu.backends.base import BackendError
from quorum_tpu.backends.tpu_backend import TpuBackend
from quorum_tpu.config import BackendSpec

BASE = {"model": "m", "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 5}


@pytest.fixture(scope="module")
def backend():
    return TpuBackend.from_spec(BackendSpec(
        name="knobs", url="tpu://llama-tiny?seed=1", model="m"))


def run(coro):
    return asyncio.run(coro)


# ---- n ---------------------------------------------------------------------

def test_n_returns_distinct_choices(backend):
    body = {**BASE, "n": 3, "temperature": 0.9, "seed": 4}
    res = run(backend.complete(body, {}, 60))
    choices = res.body["choices"]
    assert [c["index"] for c in choices] == [0, 1, 2]
    texts = {c["message"]["content"] for c in choices}
    assert len(texts) >= 2  # distinct sampling streams per choice
    assert res.body["usage"]["completion_tokens"] == 15  # summed across choices


def test_n_streaming_tags_choice_indices(backend):
    async def go():
        idxs, finishes = set(), []
        async for ch in backend.stream({**BASE, "n": 2, "stream": True}, {}, 60):
            for c in ch.get("choices") or []:
                idxs.add(c["index"])
                if c.get("finish_reason"):
                    finishes.append(c["index"])
        return idxs, finishes

    idxs, finishes = run(go())
    assert idxs == {0, 1}
    assert sorted(finishes) == [0, 1]  # one finish chunk per choice


@pytest.mark.parametrize("bad", [0, 9, -1, "3", 2.5, True])
def test_n_rejects_bad_values(backend, bad):
    with pytest.raises(BackendError) as e:
        run(backend.complete({**BASE, "n": bad}, {}, 60))
    assert e.value.status_code == 400


# ---- logprobs --------------------------------------------------------------

def test_logprobs_structure_and_consistency(backend):
    body = {**BASE, "logprobs": True, "top_logprobs": 2, "temperature": 0.0}
    res = run(backend.complete(body, {}, 60))
    choice = res.body["choices"][0]
    content = choice["logprobs"]["content"]
    assert len(content) == 5  # one entry per generated token
    for entry in content:
        assert set(entry) == {"token", "logprob", "bytes", "top_logprobs"}
        assert entry["logprob"] <= 0.0
        assert len(entry["top_logprobs"]) == 2
        assert isinstance(entry["bytes"], list)
    # greedy sampling: the sampled token IS the top-1 alternative
    e0 = content[0]
    assert e0["token"] == e0["top_logprobs"][0]["token"]
    assert e0["logprob"] == pytest.approx(e0["top_logprobs"][0]["logprob"])


def test_logprobs_absent_by_default(backend):
    res = run(backend.complete(dict(BASE), {}, 60))
    assert "logprobs" not in res.body["choices"][0]


@pytest.mark.parametrize("bad", [
    {"logprobs": "yes"},
    {"logprobs": True, "top_logprobs": 21},
    {"logprobs": True, "top_logprobs": -1},
    {"top_logprobs": 5},  # requires logprobs: true
])
def test_logprobs_rejects_bad_values(backend, bad):
    with pytest.raises(BackendError) as e:
        run(backend.complete({**BASE, **bad}, {}, 60))
    assert e.value.status_code == 400


def test_logprobs_align_with_content_under_stop(backend):
    """logprobs.content must track EMITTED content: tokens swallowed by the
    stop matcher (the stop string itself) get no entries (OpenAI 1:1
    content/logprobs alignment)."""
    # Find what the model greedily emits, pick its 3rd token's text as stop.
    probe = run(backend.complete(
        {**BASE, "max_tokens": 8, "temperature": 0.0, "logprobs": True}, {}, 60))
    entries = probe.body["choices"][0]["logprobs"]["content"]
    assert len(entries) == 8
    stop_tok = entries[3]["token"]
    if not stop_tok:
        pytest.skip("3rd token has empty text (detokenizer buffering)")

    res = run(backend.complete(
        {**BASE, "max_tokens": 8, "temperature": 0.0, "logprobs": True,
         "stop": [stop_tok]}, {}, 60))
    choice = res.body["choices"][0]
    content = choice["message"]["content"]
    lp = choice["logprobs"]["content"]
    assert stop_tok not in content  # stop string excluded from content
    # entries correspond to the emitted prefix only — joining their token
    # texts reproduces the content exactly
    assert "".join(e["token"] for e in lp) == content


def test_streaming_logprobs_align_with_streamed_content(backend):
    """Streamed logprob entries ride inside content chunks and, joined,
    reproduce exactly the streamed content (stop-swallowed text drops its
    entries)."""
    probe = run(backend.complete(
        {**BASE, "max_tokens": 8, "temperature": 0.0, "logprobs": True}, {}, 60))
    stop_tok = probe.body["choices"][0]["logprobs"]["content"][3]["token"]
    if not stop_tok:
        pytest.skip("3rd token has empty text")

    async def go():
        text, toks = [], []
        async for ch in backend.stream(
            {**BASE, "max_tokens": 8, "temperature": 0.0, "logprobs": True,
             "stop": [stop_tok], "stream": True}, {}, 60):
            for c in ch.get("choices") or []:
                delta = c.get("delta") or {}
                if delta.get("content"):
                    text.append(delta["content"])
                for e in ((c.get("logprobs") or {}).get("content") or []):
                    toks.append(e["token"])
        return "".join(text), "".join(toks)

    streamed, lp_joined = run(go())
    assert stop_tok not in streamed
    assert lp_joined == streamed


# ---- penalties -------------------------------------------------------------

def test_frequency_penalty_discourages_repeats(backend):
    base = {**BASE, "max_tokens": 12, "temperature": 0.0, "seed": 0}
    plain = run(backend.complete(base, {}, 60))
    pen = run(backend.complete({**base, "frequency_penalty": 2.0}, {}, 60))
    t_plain = plain.body["choices"][0]["message"]["content"]
    t_pen = pen.body["choices"][0]["message"]["content"]
    assert t_plain != t_pen  # the knob visibly acts on the distribution


@pytest.mark.parametrize("knob", ["presence_penalty", "frequency_penalty"])
@pytest.mark.parametrize("bad", [2.5, -2.5, "x"])
def test_penalties_reject_out_of_range(backend, knob, bad):
    with pytest.raises(BackendError) as e:
        run(backend.complete({**BASE, knob: bad}, {}, 60))
    assert e.value.status_code == 400


# ---- logit_bias ------------------------------------------------------------

def test_logit_bias_forces_token(backend):
    # +100 bias on one token makes greedy sampling emit it every step
    body = {**BASE, "max_tokens": 3, "temperature": 0.0,
            "logit_bias": {"42": 100}}
    res = run(backend.complete(body, {}, 60))
    text = res.body["choices"][0]["message"]["content"]
    assert text == backend.tokenizer.decode([42, 42, 42])


@pytest.mark.parametrize("bad", [
    {"999999": 1},        # out-of-vocab id
    {"5": 500},           # bias outside [-100, 100]
    {"x": 1},             # non-integer id
    "notadict",
])
def test_logit_bias_rejects_bad_values(backend, bad):
    with pytest.raises(BackendError) as e:
        run(backend.complete({**BASE, "logit_bias": bad}, {}, 60))
    assert e.value.status_code == 400


# ---- unsupported fields → documented 400 -----------------------------------

@pytest.mark.parametrize("field,value", [
    ("tools", [{"type": "function", "function": {"name": "f"}}]),
    ("tool_choice", "auto"),
    ("functions", [{"name": "f"}]),
    ("function_call", "auto"),
    # response_format types are now IMPLEMENTED (docs/structured_output.md,
    # tests/test_constrained_decoding.py); malformed shapes and schemas
    # outside the supported subset stay 400s:
    ("response_format", {"type": "json_schema", "json_schema": {}}),
    ("response_format", {"type": "json_schema",
                         "json_schema": {"schema": {"$ref": "#/x"}}}),
    ("response_format", {"type": "regex", "pattern": "("}),
    ("response_format", {"type": "xml"}),
])
def test_unsupported_fields_rejected(backend, field, value):
    with pytest.raises(BackendError) as e:
        run(backend.complete({**BASE, field: value}, {}, 60))
    assert e.value.status_code == 400
    assert e.value.body["error"]["type"] == "invalid_request_error"


def test_response_format_regex_constrains_output(backend):
    """Structured output's fast-tier smoke: a regex response_format is
    enforced on device (the full json_schema/pipeline matrix lives in
    tests/test_constrained_decoding.py)."""
    res = run(backend.complete(
        {**BASE, "max_tokens": 8, "temperature": 0.9, "seed": 2,
         "response_format": {"type": "regex", "pattern": "yes|no|maybe"}},
        {}, 60))
    choice = res.body["choices"][0]
    assert choice["message"]["content"] in ("yes", "no", "maybe")
    assert choice["finish_reason"] == "stop"


def test_response_format_text_accepted(backend):
    res = run(backend.complete(
        {**BASE, "response_format": {"type": "text"}}, {}, 60))
    assert res.status_code == 200


@pytest.mark.parametrize("field", ["user", "store", "metadata", "service_tier"])
def test_metadata_fields_accepted_and_ignored(backend, field):
    res = run(backend.complete({**BASE, field: "anything"}, {}, 60))
    assert res.status_code == 200


# ---- n>1 isolation: one choice finishing must not truncate siblings --------

class _MultiScriptEngine:
    """Stub engine where each submitted choice gets its own token script,
    replayed with the real engine's contract: stream_results sets the
    request's cancel event in its finally (slot release)."""

    def __init__(self, scripts):
        from quorum_tpu.models.model_config import MODEL_PRESETS

        self.spec = MODEL_PRESETS["llama-tiny"]
        self.scripts = list(scripts)
        self._i = 0

    def submit(self, prompt_ids, *, cancel=None, **kw):
        script = self.scripts[self._i]
        self._i += 1
        return (script, cancel)

    def stream_results(self, req):
        import time

        script, cancel = req
        try:
            for t in script:
                if cancel is not None and cancel.is_set():
                    return
                time.sleep(0.005)
                yield t
        finally:
            if cancel is not None:
                cancel.set()


def test_one_choice_finishing_does_not_truncate_siblings():
    """Choice 0 hits EOS after 1 token; choice 1 must still produce its full
    8 tokens (per-choice cancel events — a shared event let the first
    finisher's slot release abort every sibling)."""
    eng = None

    def build():
        nonlocal eng
        b = TpuBackend.from_spec(BackendSpec(
            name="iso", url="tpu://llama-tiny?seed=3", model="m"))
        eos = b.tokenizer.eos_id
        eng = _MultiScriptEngine([[7, eos], [11] * 8])
        b.engine = eng
        return b

    b = build()
    res = run(b.complete({**BASE, "n": 2, "max_tokens": 8}, {}, 60))
    choices = res.body["choices"]
    assert choices[0]["finish_reason"] == "stop"
    assert choices[1]["finish_reason"] == "length"
    assert choices[1]["message"]["content"] == b.tokenizer.decode([11] * 8)


# ---- drain park: non-streaming must shed, never return truncated text ------

class _ParkingEngine:
    """Stub engine honoring the drain-park contract: a few tokens, then
    ``req.parked = True`` set BEFORE the stream ends (engine
    _sweep_drain_parks semantics)."""

    def __init__(self, tokens):
        from quorum_tpu.models.model_config import MODEL_PRESETS

        self.spec = MODEL_PRESETS["llama-tiny"]
        self.tokens = list(tokens)

    def submit(self, prompt_ids, *, cancel=None, **kw):
        import types

        return types.SimpleNamespace(parked=False, lp=[], cancel=cancel)

    def stream_results(self, req):
        yield from self.tokens
        req.parked = True


def test_drain_park_non_streaming_is_retryable_503():
    """A drain-parked request on the NON-streaming path has no resume
    journal: the partial text must become a retryable 503 overload (the
    router re-places the whole request on a sibling), never a truncated
    200 with finish_reason "length"."""
    b = TpuBackend.from_spec(BackendSpec(
        name="park", url="tpu://llama-tiny?seed=5", model="m"))
    b.engine = _ParkingEngine([7, 8, 9])
    with pytest.raises(BackendError) as ei:
        run(b.complete({**BASE, "max_tokens": 8}, {}, 60))
    assert ei.value.status_code == 503
    assert ei.value.body["error"]["type"] == "overloaded_error"
    assert "draining" in str(ei.value)
    assert "Retry-After" in ei.value.headers


# ---- proxy-level validation & status relay (app layer) ---------------------

async def _app_post(config, body, **fakes):
    from tests.conftest import make_client

    async with make_client(config, **fakes) as client:
        return await client.post(
            "/v1/chat/completions", json=body,
            headers={"Authorization": "Bearer x"})


def _two_fake_config():
    return {
        "settings": {"timeout": 30},
        "primary_backends": [
            {"name": "A", "url": "http://a.test", "model": "m"},
            {"name": "B", "url": "http://b.test", "model": "m"},
        ],
        "iterations": {"aggregation": {"strategy": "concatenate"}},
        "strategy": {"concatenate": {"separator": "+"},
                     "aggregate": {"source_backends": "all",
                                   "aggregator_backend": ""}},
    }


@pytest.mark.parametrize("bad", [
    {"n": 0}, {"n": "x"}, {"logprobs": "yes"}, {"top_logprobs": 21},
    {"presence_penalty": 5}, {"frequency_penalty": -3},
    {"logit_bias": {"x": 1}}, {"logit_bias": {"5": 500}},
])
async def test_malformed_knobs_rejected_before_fanout(bad):
    """docs/api.md: malformed knob values are ONE 400 before fan-out — no
    backend sees the request (not N failures, not a 200 from a permissive
    backend)."""
    from quorum_tpu.backends.fake import FakeBackend

    fakes = dict(A=FakeBackend("A", text="a"), B=FakeBackend("B", text="b"))
    resp = await _app_post(
        _two_fake_config(),
        {"model": "m", "messages": [{"role": "user", "content": "q"}], **bad},
        **fakes)
    assert resp.status_code == 400, resp.text
    assert resp.json()["error"]["type"] == "invalid_request_error"
    assert fakes["A"].calls == [] and fakes["B"].calls == []


async def test_backend_503_relayed_not_collapsed():
    """A tpu:// backend's 503 overloaded_error must reach the client as a
    503, not be collapsed into the all-failed 500 proxy_error
    (docs/api.md error table)."""
    from quorum_tpu.backends.fake import FakeBackend
    from quorum_tpu import oai

    overloaded = BackendError(
        "queue full", status_code=503,
        body=oai.error_body("queue full", type_="overloaded_error", code=503))
    config = {
        "settings": {"timeout": 30},
        "primary_backends": [{"name": "A", "url": "http://a.test", "model": "m"}],
    }
    resp = await _app_post(
        config,
        {"model": "m", "messages": [{"role": "user", "content": "q"}]},
        A=FakeBackend("A", fail_with=overloaded))
    assert resp.status_code == 503
    assert resp.json()["error"]["type"] == "overloaded_error"
