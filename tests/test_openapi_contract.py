"""Conformance of the live server against the vendored OpenAPI document.

The reference anchors compatibility on a vendored machine-readable OpenAPI
spec (/root/reference/api_reference/chat_completions.yaml); ours is
``api/openapi.yaml`` (VERDICT r3 missing item 1). The golden fixtures pin
exact wire *shapes*; this module pins the *schema document itself* — every
served route is documented, every documented route is served, and live
responses (success bodies, SSE frames, every error family) validate against
the component schemas with the jsonschema library. A drift in either the
server or the document fails here.
"""

import json
from pathlib import Path

import pytest

jsonschema = pytest.importorskip(
    "jsonschema",
    reason="conformance checks need the jsonschema validator (CI installs "
           "it; `pip install jsonschema` locally)")
import yaml

from tests.conftest import make_client
from tests.test_contract_fixtures import (
    FIXTURES,
    parallel_config,
    single_backend_config,
)

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

DOC = yaml.safe_load(
    (Path(__file__).parent.parent / "api" / "openapi.yaml").read_text())


def schema_for(name: str) -> dict:
    """A self-contained validator schema: top-level $ref into the document's
    components, with the components carried along for resolution."""
    return {"$ref": f"#/components/schemas/{name}",
            "components": DOC["components"]}


def check(name: str, instance) -> None:
    jsonschema.validate(
        instance, schema_for(name),
        cls=jsonschema.validators.Draft202012Validator)


# ---- document structure ----------------------------------------------------

def test_document_paths_match_served_routes():
    """The doc's path set IS the served surface (each under both the ""
    and "/v1" servers — app.py registers both prefixes). Paths flagged
    ``x-router-only: true`` are served by the router process
    (quorum_tpu/router/app.py), not by replicas — the replica partition
    below is what a serving replica exposes."""
    router_only = {p for p, item in DOC["paths"].items()
                   if item.get("x-router-only")}
    assert router_only == {"/debug/router/timeline",
                           "/debug/fleet/timeline"}
    assert set(DOC["paths"]) - router_only == {
        "/chat/completions", "/completions", "/embeddings", "/health",
        "/ready", "/models", "/metrics", "/debug/traces",
        "/debug/traces/{request_id}", "/debug/engine/timeline",
        "/debug/prefix/chunks", "/debug/profile", "/debug/telemetry",
        "/admin/drain", "/admin/undrain"}
    assert [s["url"] for s in DOC["servers"]] == ["/", "/v1"]
    post = DOC["paths"]["/chat/completions"]["post"]
    assert set(post["responses"]) == {
        "200", "400", "401", "422", "500", "503", "504"}
    # The 503/504 shapes carry Retry-After (docs/robustness.md).
    for ref, resp in (("Overloaded", "503"), ("GatewayTimeout", "504")):
        assert post["responses"][resp]["$ref"].endswith(ref)
        assert "Retry-After" in DOC["components"]["responses"][ref]["headers"]
    # Streaming and JSON bodies both documented on the 200.
    assert set(post["responses"]["200"]["content"]) == {
        "application/json", "text/event-stream"}


def test_component_schemas_are_valid_jsonschema():
    for name, schema in DOC["components"]["schemas"].items():
        jsonschema.validators.Draft202012Validator.check_schema(schema)
        # and resolvable end-to-end (a dangling $ref would raise here)
        jsonschema.validators.Draft202012Validator(
            schema_for(name)).is_valid({})


def test_error_type_enum_matches_docs_table():
    enum = DOC["components"]["schemas"]["ErrorResponse"][
        "properties"]["error"]["properties"]["type"]["enum"]
    assert set(enum) == {"invalid_request_error", "auth_error",
                        "configuration_error", "proxy_error",
                        "overloaded_error", "timeout_error",
                        "grammar_error", "conflict_error"}


def test_response_format_schema_accepts_documented_variants():
    """The structured-output request surface (docs/structured_output.md):
    every documented variant validates; junk shapes don't."""
    for rf in ({"type": "text"},
               {"type": "json_object"},
               {"type": "json_schema",
                "json_schema": {"name": "t", "schema": {"type": "object"}}},
               {"type": "regex", "pattern": "yes|no"}):
        check("ResponseFormat", rf)
        check("CreateChatCompletionRequest",
              {"messages": [{"role": "user", "content": "x"}],
               "response_format": rf})
    import jsonschema as _js
    for bad in ({"type": "xml"}, {"type": 3}, {}):
        with pytest.raises(_js.ValidationError):
            check("ResponseFormat", bad)


def test_fixture_requests_validate_against_request_schema():
    """Every golden fixture's request body is a valid
    CreateChatCompletionRequest."""
    for path in sorted(FIXTURES.glob("*.json")):
        fx = json.loads(path.read_text())
        check("CreateChatCompletionRequest", fx["request"])


# ---- live conformance ------------------------------------------------------

BODY = {"model": "tiny", "max_tokens": 4, "temperature": 0.0,
        "messages": [{"role": "user", "content": "conformance probe"}]}


async def test_live_nonstream_response_conforms():
    async with make_client(single_backend_config()) as client:
        resp = await client.post(
            "/v1/chat/completions", json=BODY,
            headers={"Authorization": "Bearer t"})
        assert resp.status_code == 200
        assert resp.headers.get("x-request-id")
        check("CreateChatCompletionResponse", resp.json())


async def test_live_stream_frames_conform():
    async with make_client(parallel_config()) as client:
        resp = await client.post(
            "/v1/chat/completions",
            json={**BODY, "stream": True,
                  "stream_options": {"include_usage": True}},
            headers={"Authorization": "Bearer t"})
        assert resp.status_code == 200
        lines = [ln for ln in resp.text.splitlines()
                 if ln.startswith("data: ")]
    assert lines[-1] == "data: [DONE]"
    frames = [json.loads(ln[len("data: "):]) for ln in lines[:-1]]
    assert frames, "no SSE frames"
    for frame in frames:
        check("CreateChatCompletionStreamResponse", frame)


async def test_live_completions_conform():
    async with make_client(single_backend_config()) as client:
        gen = await client.post(
            "/v1/completions",
            json={"model": "tiny", "prompt": "conformance", "max_tokens": 4,
                  "temperature": 0.0, "logprobs": 2},
            headers={"Authorization": "Bearer t"})
        assert gen.status_code == 200, gen.text
        check("CreateCompletionResponse", gen.json())
        score = await client.post(
            "/v1/completions",
            json={"model": "tiny", "prompt": "score probe", "max_tokens": 0,
                  "echo": True, "logprobs": 1},
            headers={"Authorization": "Bearer t"})
        assert score.status_code == 200, score.text
        check("CreateCompletionResponse", score.json())
    check("CreateCompletionRequest",
          {"prompt": "x", "max_tokens": 0, "echo": True, "logprobs": 2})


async def test_live_embeddings_conform():
    async with make_client(single_backend_config()) as client:
        resp = await client.post(
            "/v1/embeddings",
            json={"model": "tiny", "input": ["conformance", "probe"]},
            headers={"Authorization": "Bearer t"})
        assert resp.status_code == 200, resp.text
        check("CreateEmbeddingResponse", resp.json())
        bad = await client.post(
            "/v1/embeddings", json={"model": "tiny", "input": []},
            headers={"Authorization": "Bearer t"})
        assert bad.status_code == 400
        check("ErrorResponse", bad.json())
    check("CreateEmbeddingRequest",
          {"input": "x", "encoding_format": "base64", "dimensions": 16})


async def test_live_aux_endpoints_conform():
    async with make_client(single_backend_config()) as client:
        health = await client.get("/health")
        check("HealthResponse", health.json())
        ready = await client.get("/ready")
        check("ReadyResponse", ready.json())
        models = await client.get("/v1/models")
        check("ModelList", models.json())
        metrics = await client.get("/metrics")
        assert metrics.status_code == 200
        assert metrics.text.startswith("#") or "quorum_tpu" in metrics.text
        timeline = await client.get("/debug/engine/timeline")
        check("EngineTimeline", timeline.json())
        perfetto = await client.get("/debug/engine/timeline?format=perfetto")
        assert "traceEvents" in perfetto.json()
        bad_fmt = await client.get("/debug/engine/timeline?format=nope")
        assert bad_fmt.status_code == 400
        check("ErrorResponse", bad_fmt.json())
        telemetry = await client.get("/debug/telemetry")
        assert telemetry.status_code == 200
        check("TelemetrySnapshot", telemetry.json())
        # On-demand profile: a tiny capture conforms; out-of-range 400s;
        # a concurrent request hits the single-flight 409 (exercised via
        # the shared profiler lock in tests/test_telemetry.py).
        prof = await client.post("/v1/debug/profile?seconds=0.05")
        assert prof.status_code == 200, prof.text
        check("ProfileResult", prof.json())
        bad = await client.post("/debug/profile?seconds=0")
        assert bad.status_code == 400
        check("ErrorResponse", bad.json())


@pytest.mark.parametrize("req,headers,status,err_type", [
    # tools → tpu:// rejection (documented 400 family)
    ({**BODY, "tools": [{"type": "function"}]},
     {"Authorization": "Bearer t"}, 400, "invalid_request_error"),
    # missing auth entirely
    (BODY, {}, 401, "auth_error"),
    # out-of-range n
    ({**BODY, "n": 99}, {"Authorization": "Bearer t"}, 400,
     "invalid_request_error"),
    # malformed response_format: caught pre-fan-out by validate_request_body
    ({**BODY, "response_format": {"type": "json_schema"}},
     {"Authorization": "Bearer t"}, 400, "invalid_request_error"),
    # schema outside the constrained-decoding subset: the backend's 400
    ({**BODY, "response_format": {
        "type": "json_schema",
        "json_schema": {"schema": {"$ref": "#/nope"}}}},
     {"Authorization": "Bearer t"}, 400, "invalid_request_error"),
])
async def test_live_errors_conform(req, headers, status, err_type,
                                   monkeypatch):
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    async with make_client(single_backend_config()) as client:
        resp = await client.post("/v1/chat/completions", json=req,
                                 headers=headers)
        assert resp.status_code == status, resp.text
        body = resp.json()
        check("ErrorResponse", body)
        assert body["error"]["type"] == err_type


def test_no_fanout_routes_document_model_not_found():
    """ADVICE r4: the no-fan-out endpoints 404 on an unserved model; the
    contract documents the full status family for both."""
    for route in ("/completions", "/embeddings"):
        post = DOC["paths"][route]["post"]
        assert {"200", "400", "401", "404", "500", "503"} <= set(
            post["responses"]), route


async def test_live_constrained_response_and_dead_end_conform():
    """Structured output on the wire: a json_schema request returns a
    conforming 200 whose content parses; a grammar no token can satisfy
    (vocab too small to spell '{') returns the documented 422
    grammar_error shape."""
    cfg = {
        "settings": {"timeout": 300},
        "primary_backends": [
            {"name": "LLM1", "url": "tpu://llama-tiny?seed=1",
             "model": "tiny"},
        ],
    }
    rf = {"type": "json_schema", "json_schema": {"schema": {
        "type": "object", "properties": {"ok": {"type": "boolean"}}}}}
    async with make_client(cfg) as client:
        resp = await client.post(
            "/v1/chat/completions",
            json={**BODY, "max_tokens": 32, "response_format": rf},
            headers={"Authorization": "Bearer t"})
        assert resp.status_code == 200, resp.text
        body = resp.json()
        check("CreateChatCompletionResponse", body)
        content = body["choices"][0]["message"]["content"]
        assert isinstance(json.loads(content).get("ok"), bool)
        assert body["choices"][0]["finish_reason"] == "stop"

    tiny = {
        "settings": {"timeout": 300},
        "primary_backends": [
            {"name": "LLM1", "url": "tpu://llama-tiny?vocab_size=20&seed=1",
             "model": "tiny"},
        ],
    }
    async with make_client(tiny) as client:
        resp = await client.post(
            "/v1/chat/completions",
            json={**BODY, "response_format": rf},
            headers={"Authorization": "Bearer t"})
        assert resp.status_code == 422, resp.text
        body = resp.json()
        check("ErrorResponse", body)
        assert body["error"]["type"] == "grammar_error"


# ---- quorum fan-out (docs/quorum.md) ---------------------------------------

QUORUM_REASONS = {"member_failed", "stream_broken", "resume_diverged",
                  "no_content"}


def test_quorum_knob_and_headers_documented():
    """The quorum request knob, the X-Quorum-* response headers, and the
    body summary object are all in the document, with reason enums
    matching the fan-out code's degrade vocabulary."""
    req = DOC["components"]["schemas"]["CreateChatCompletionRequest"]
    q = req["properties"]["quorum"]
    assert (q["type"], q["minimum"], q["maximum"]) == ("integer", 1, 8)
    from quorum_tpu.quorum.fanout import MAX_QUORUM
    assert q["maximum"] == MAX_QUORUM

    hdrs = DOC["components"]["headers"]
    for name in ("XQuorumMembers", "XQuorumServed", "XQuorumReplicas",
                 "XQuorumDegraded", "XQuorumAggregateDegraded",
                 "XQuorumAggregateError"):
        assert name in hdrs, name
    assert set(hdrs["XQuorumDegraded"]["schema"]["enum"]) == QUORUM_REASONS
    assert set(hdrs["XQuorumAggregateDegraded"]["schema"]["enum"]) == {
        "no_aggregator", "no_credentials", "error", "empty"}

    ok_headers = DOC["paths"]["/chat/completions"]["post"][
        "responses"]["200"]["headers"]
    for wire in ("X-Quorum-Members", "X-Quorum-Served", "X-Quorum-Replicas",
                 "X-Quorum-Degraded", "X-Quorum-Aggregate-Degraded",
                 "X-Quorum-Aggregate-Error"):
        assert wire in ok_headers, wire

    summary = DOC["components"]["schemas"]["QuorumSummary"]
    reason = summary["properties"]["degraded"]["items"][
        "properties"]["reason"]
    assert set(reason["enum"]) == QUORUM_REASONS


def test_quorum_request_and_summary_schemas_validate():
    import jsonschema as _js
    base = {"messages": [{"role": "user", "content": "x"}]}
    check("CreateChatCompletionRequest", {**base, "quorum": 3})
    check("CreateChatCompletionRequest", {**base, "quorum": 1})
    for bad in (0, 9, "3", 2.5):
        with pytest.raises(_js.ValidationError):
            check("CreateChatCompletionRequest", {**base, "quorum": bad})
    check("QuorumSummary", {"members": 3, "served": 2,
                            "replicas": ["r0", "r2"],
                            "degraded": [{"member": 1,
                                          "reason": "member_failed"}]})
    with pytest.raises(_js.ValidationError):
        check("QuorumSummary", {"members": 3, "served": 2,
                                "replicas": ["r0"],
                                "degraded": [{"member": 1,
                                              "reason": "gremlins"}]})


async def test_live_quorum_response_conforms():
    """A real quorum=3 combine from the router tier validates against the
    response schema — including the quorum summary object — and carries
    the documented headers."""
    from tests.test_router import _Cluster
    async with _Cluster(3) as c:
        resp = await c.chat([{"role": "user", "content": "conformance"}],
                            quorum=3, max_tokens=8)
    assert resp.status_code == 200, resp.text
    body = resp.json()
    check("CreateChatCompletionResponse", body)
    check("QuorumSummary", body["quorum"])
    assert resp.headers["x-quorum-members"] == "3"
    assert resp.headers["x-quorum-served"] == "3"
    assert len(resp.headers["x-quorum-replicas"].split(",")) == 3
    assert "x-quorum-degraded" not in resp.headers


async def test_live_model_not_found_conforms():
    async with make_client(single_backend_config()) as client:
        resp = await client.post(
            "/v1/completions",
            json={"model": "no-such-model", "prompt": "x", "max_tokens": 1},
            headers={"Authorization": "Bearer t"})
        assert resp.status_code == 404, resp.text
        body = resp.json()
        check("ErrorResponse", body)
        assert body["error"]["code"] == "model_not_found"
