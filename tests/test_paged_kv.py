"""Paged KV slot memory (``kv_pages=1``, ISSUE 17 acceptance):

- **token identity**: every decode shape — greedy, sampled, EOS cut,
  constrained grammar, deep ring (decode_pipeline=4 × decode_loop=4),
  prompt-lookup speculation, members=M, kv_quant=int8, zero_drain,
  prefix-store restore — generates EXACTLY what the dense rectangle
  generates. Paging is a capacity optimization, never a semantic change.
- **aliasing**: a tier-0 prefix hit installs page *references* (refcount
  bump + table rewrite) — the alias counter ticks and the pool does not
  pay a second copy of the shared span; a reuse length landing mid-page
  copies exactly the one boundary page (copy-on-write counter).
- **admission-time shed**: a request whose full page span can never fit
  the pool sheds synchronously (QueueFullError → 503 + Retry-After at the
  server); transient exhaustion queues — a running stream can never OOM
  because admission pre-reserves its whole span.
- **program-key contract**: paged programs live under "paged"-tagged
  compile-budget families; every ``kv_pages=0`` engine's keys stay
  byte-for-byte the dense tuples.

Host-side PageAllocator bookkeeping (refcounts, retained-chain LRU,
reclaim) and the pure device ops are fast-tier; engine-scale legs are
slow-tier like every other engine test."""

import dataclasses
import threading
import types

import numpy as np
import pytest

from quorum_tpu.analysis import budget
from quorum_tpu.cache.paging import (
    PageAllocator,
    PagedKV,
    init_paged_cache,
    page_read,
    paged_slice_rows,
    paged_write_rows,
    validate_page_config,
)
from quorum_tpu.engine.engine import InferenceEngine, QueueFullError
from quorum_tpu.models import resolve_spec
from quorum_tpu.models.model_config import MODEL_PRESETS
from quorum_tpu.ops.sampling import SamplerConfig

slow = pytest.mark.slow

SPEC = dataclasses.replace(MODEL_PRESETS["llama-tiny"], max_seq=128)
GREEDY = SamplerConfig(temperature=0.0)
SAMPLED = SamplerConfig(temperature=0.9, top_p=0.9)


# ---- PageAllocator bookkeeping (pure host, fast tier) ----------------------


def test_alloc_assign_release_refcounts():
    a = PageAllocator(4, 16)
    pages = a.alloc(3)
    assert pages == [1, 2, 3] and a.free_pages == 1
    a.assign(0, pages)
    a.release(0, keep_tokens=20)          # 20 tokens -> 2 pages retained
    assert a.retained_chain(0) == [1, 2]
    assert a.free_pages == 2              # tail page freed
    assert a.allocated_pages == 2


def test_alloc_shortfall_returns_none_not_partial():
    a = PageAllocator(2, 16)
    assert a.alloc(3) is None
    assert a.free_pages == 2              # nothing leaked


def test_adopt_transfers_refs_without_copy():
    a = PageAllocator(4, 16)
    a.assign(1, a.alloc(2))
    a.release(1, keep_tokens=32)
    refs_before = list(a.refs)
    chain = a.adopt(1)
    assert chain == [1, 2]
    assert a.refs == refs_before          # ref ownership moved, not bumped
    assert a.retained_chain(1) is None


def test_share_aliases_by_refcount_and_survives_donor_release():
    a = PageAllocator(4, 16)
    donor = a.alloc(2)
    a.assign(0, donor)
    a.release(0, keep_tokens=32)          # retained donor chain
    aliased = a.share(a.retained_chain(0))
    a.assign(1, aliased + a.alloc(1))
    assert all(a.is_shared(p) for p in aliased)
    # evicting the donor's retained entry must NOT free aliased pages
    a.drop_retained(0)
    assert a.free_pages == 1
    a.release(1, keep_tokens=0)
    assert a.free_pages == 4              # last ref dropped -> all free


def test_extend_appends_without_disturbing_chain():
    a = PageAllocator(4, 16)
    a.assign(2, a.alloc(1))
    head = list(a.chain(2))
    a.extend(2, a.alloc(2))
    assert a.chain(2)[: len(head)] == head
    assert len(a.chain(2)) == 3


def test_evict_lru_order_and_protect():
    a = PageAllocator(6, 16)
    for row in (0, 1, 2):
        a.assign(row, a.alloc(2))
        a.release(row, keep_tokens=32)
    a.touch(0)                            # 0 becomes MRU; LRU order: 1, 2, 0
    assert a.evict_lru(protect=(1,)) == 2
    assert a.evict_lru() == 1
    assert a.evict_lru(protect=(0,)) is None


def test_reclaimable_counts_only_sole_reference_pages():
    a = PageAllocator(6, 16)
    a.assign(0, a.alloc(2))
    a.release(0, keep_tokens=32)
    live = a.share(a.retained_chain(0))   # alias retained pages into row 1
    a.assign(1, live)
    assert a.reclaimable_pages() == 0     # evicting 0 frees nothing: aliased
    a.assign(2, a.alloc(2))
    a.release(2, keep_tokens=32)
    assert a.reclaimable_pages() == 2
    assert a.reclaimable_pages(protect=(2,)) == 0


def test_release_zero_keep_frees_everything_and_reset():
    a = PageAllocator(3, 16)
    a.assign(0, a.alloc(3))
    a.release(0, keep_tokens=0)
    assert a.free_pages == 3 and a.retained_chain(0) is None
    a.assign(1, a.alloc(2))
    a.reset()
    assert a.free_pages == 3 and a.chains == {}


def test_page_zero_is_never_handed_out():
    a = PageAllocator(3, 4)
    assert 0 not in a.alloc(3)


# ---- config validation (fast tier) -----------------------------------------


def test_validate_page_config_rejects_bad_sizes():
    with pytest.raises(ValueError, match="power of two"):
        validate_page_config(128, 24)
    with pytest.raises(ValueError, match="divide max_seq"):
        validate_page_config(96, 64)
    validate_page_config(128, 32)         # ok


# ---- program-key contract (fast tier) --------------------------------------


def _keyer(**over):
    """Call the real _decode_key with a minimal stand-in self — pins the
    dense tuples without paying an engine construction."""
    ns = types.SimpleNamespace(decode_pp=1, kv_pages=False, _g_bucket=256)
    for k, v in over.items():
        setattr(ns, k, v)
    return lambda *a, **kw: InferenceEngine._decode_key(ns, *a, **kw)


def test_dense_decode_keys_are_byte_identical_to_pre_paged():
    """kv_pages=0 engines must compile and dispatch the exact pre-paged
    program variants: the unconstrained single-chunk key stays the bare
    3-tuple, the loop/dfa tags stay in their pinned positions."""
    k = _keyer()
    assert k(4, False, 128, False) == (4, False, 128)
    assert k(4, True, 64, True) == ("dfa", 4, True, 64, 256)
    assert k(4, False, 128, False, n_chunks=4) == ("loop", 4, 4, False, 128)
    assert k(2, False, 32, True, n_chunks=2) == (
        "loop", 2, "dfa", 2, False, 32, 256)


def test_paged_decode_keys_prefix_the_dense_tuples():
    k = _keyer(kv_pages=True)
    assert k(4, False, 128, False) == ("paged", 4, False, 128)
    assert k(4, False, 128, False, n_chunks=4) == (
        "paged", "loop", 4, 4, False, 128)
    assert k(4, True, 64, True) == ("paged", "dfa", 4, True, 64, 256)


def test_budget_classifies_paged_families():
    cases = {
        ("paged", 4, False, 128): "paged_plain",
        ("paged", "dfa", 4, False, 128, 2): "paged_dfa",
        ("paged", "loop", 4, 4, False, 128): "paged_loop",
        ("paged", "loop", 4, "dfa", 4, False, 128, 2): "paged_loop_dfa",
        ("paged", "verify", 5, False, 128): "paged_verify",
        ("paged", "dfa_verify", 5, False, 128, 2): "paged_dfa_verify",
    }
    for key, fam in cases.items():
        assert budget.classify_decode_key(key) == fam
    assert budget.classify_admit_key(("page_copy",)) == "page_copy"
    with pytest.raises(budget.UnbudgetedProgramKey):
        budget.classify_decode_key(("paged", "pp", 4, False, 128))


# ---- pure device ops (small arrays, fast tier) ------------------------------

OPS_SPEC = resolve_spec("llama-tiny", {"max_seq": "32"})


def test_wire_roundtrip_and_zero_sink():
    """paged_write_rows → paged_slice_rows is the identity on the written
    span, the zero sink stays zero, and unreserved tail reads gather
    zeros (page_read past the chain hits the sink)."""
    ck, _ = init_paged_cache(OPS_SPEC, batch=2, n_pages=8, page_size=8)
    ell, k, hd = OPS_SPEC.n_layers, OPS_SPEC.n_kv_heads, OPS_SPEC.head_dim
    # reserve pages 1..4 for row 0 host-side, upload the table
    tab = np.zeros((2, 4), np.int32)
    tab[0] = [1, 2, 3, 4]
    ck = PagedKV(ck.pool, np.broadcast_to(tab, (ell,) + tab.shape).copy())
    rng = np.random.default_rng(0)
    chunk = rng.standard_normal((ell, k, 20, hd)).astype(np.float32)
    ck = paged_write_rows(ck, chunk, 0, 3)
    out = np.asarray(paged_slice_rows(ck, 0, 3, 20))
    np.testing.assert_allclose(out, chunk, rtol=1e-2, atol=1e-2)  # bf16 pool
    pool = np.asarray(ck.pool)
    assert not pool[:, 0].any(), "zero sink was written"
    # per-layer window read: row 1 has no pages -> all zeros via the sink
    layer0 = PagedKV(ck.pool[0], ck.table[0])
    win = np.asarray(page_read(layer0, 16))
    assert not win[1].any()
    np.testing.assert_allclose(win[0, :, 3:16], chunk[0, :, :13],
                               rtol=1e-2, atol=1e-2)


def test_int8_wire_roundtrip():
    ck, _ = init_paged_cache(OPS_SPEC, batch=1, n_pages=4, page_size=8,
                             kv_quant="int8")
    ell, k, hd = OPS_SPEC.n_layers, OPS_SPEC.n_kv_heads, OPS_SPEC.head_dim
    tab = np.zeros((1, 4), np.int32)
    tab[0] = [1, 2, 0, 0]
    ck = PagedKV(ck.pool, np.broadcast_to(tab, (ell,) + tab.shape).copy())
    rng = np.random.default_rng(1)
    q8 = rng.integers(-127, 127, (ell, k, 10, hd), dtype=np.int8)
    sc = rng.random((ell, k, 10)).astype(np.float32)
    ck = paged_write_rows(ck, (q8, sc), 0, 0)
    oq, os_ = paged_slice_rows(ck, 0, 0, 10)
    np.testing.assert_array_equal(np.asarray(oq), q8)
    np.testing.assert_allclose(np.asarray(os_), sc, rtol=1e-6)


# ---- engine composition rejections (slow: engine-scale) ---------------------


@slow
def test_kv_pages_rejects_unsupported_knobs():
    with pytest.raises(ValueError, match="ensemble"):
        InferenceEngine(SPEC, kv_pages=True, ensemble=2)
    with pytest.raises(ValueError, match="draft model"):
        InferenceEngine(SPEC, kv_pages=True,
                        draft_spec=MODEL_PRESETS["llama-tiny"])
    with pytest.raises(ValueError, match="power of two"):
        InferenceEngine(SPEC, kv_pages=True, kv_page_size=24)


# ---- token-identity legs (slow: engine-scale) -------------------------------


def _pair(**kw):
    dense = InferenceEngine(SPEC, seed=0, **kw)
    paged = InferenceEngine(SPEC, seed=0, kv_pages=True, **kw)
    return dense, paged


def _gen(eng, p, n, sampler=GREEDY, seed=0, member=0):
    return list(eng.generate_stream(p, max_new_tokens=n, sampler=sampler,
                                    seed=seed, member=member))


@slow
def test_paged_matches_dense_and_budget_families():
    dense, paged = _pair(n_slots=4, prefill_chunk=16)
    try:
        for p in ([5, 6, 7, 8, 9], [11, 12, 13], list(range(3, 40))):
            assert _gen(dense, p, 12) == _gen(paged, p, 12)
        # EOS cut: force a stop on the token the stream actually emits
        ref = _gen(dense, [5, 6, 7], 8)
        eos = ref[1]
        a = dense.generate([5, 6, 7], max_new_tokens=8, sampler=GREEDY,
                           eos_id=eos)
        b = paged.generate([5, 6, 7], max_new_tokens=8, sampler=GREEDY,
                           eos_id=eos)
        assert a.token_ids == b.token_ids
        assert b.finish_reason == a.finish_reason == "stop"
        # every compiled key classifies into a paged family; dense engine
        # compiled zero paged programs
        fams = budget.decode_families(paged._decode_cache)
        assert fams and all(f.startswith("paged_") for f in fams)
        budget.admit_families(paged._admit_cache)  # raises on unknown keys
        assert not any(f.startswith("paged_")
                       for f in budget.decode_families(dense._decode_cache))
        m = paged.metrics()
        assert m["kv_pages"] == 1 and m["kv_page_size"] == 16
        assert m["kv_pages_allocated"] + m["kv_pages_free"] == \
            paged.kv_pool_pages
    finally:
        dense.shutdown()
        paged.shutdown()


@slow
def test_paged_matches_dense_deep_ring_spec():
    """decode_pipeline=4 × decode_loop=4 with prompt-lookup speculation:
    the repetitive prompt makes the verify program actually fire."""
    dense, paged = _pair(n_slots=3, prefill_chunk=16, decode_pipeline=4,
                         decode_loop=4, spec_decode=4)
    try:
        for s in (GREEDY, SAMPLED):
            for p in ([5, 6, 7], list(range(3, 45)), [7, 8, 9, 10] * 8):
                assert _gen(dense, p, 20, s, seed=7) == \
                    _gen(paged, p, 20, s, seed=7)
        assert paged.metrics()["spec_turns_total"] >= 1
        assert paged.metrics()["spec_turns_total"] == \
            dense.metrics()["spec_turns_total"]
    finally:
        dense.shutdown()
        paged.shutdown()


@slow
def test_paged_matches_dense_constrained():
    from quorum_tpu.constrain import compile_response_format
    from quorum_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer(SPEC.vocab_size)
    schema = {"type": "object", "properties": {"ok": {"type": "boolean"}}}
    g = compile_response_format(
        {"type": "json_schema", "json_schema": {"schema": schema}},
        tok, SPEC.vocab_size)
    dense, paged = _pair(n_slots=2, prefill_chunk=16)
    try:
        outs = []
        for eng in (dense, paged):
            req = eng.submit(tok.encode("go"), max_new_tokens=48,
                             sampler=SamplerConfig(temperature=0.8), seed=3,
                             eos_id=tok.eos_id, grammar=g)
            outs.append(list(eng.stream_results(req)))
        assert outs[0] == outs[1]
    finally:
        dense.shutdown()
        paged.shutdown()


@slow
def test_paged_matches_dense_zero_drain_members_int8():
    for kw in (dict(n_slots=2, prefill_chunk=16, zero_drain=True),
               dict(n_slots=2, prefill_chunk=16, kv_quant="int8")):
        dense, paged = _pair(**kw)
        try:
            for p in ([5, 6, 7, 9], list(range(3, 40))):
                assert _gen(dense, p, 10) == _gen(paged, p, 10)
        finally:
            dense.shutdown()
            paged.shutdown()
    dense, paged = _pair(n_slots=2, prefill_chunk=16, members=2)
    try:
        for member in (0, 1):
            for p in ([5, 6, 7, 9], list(range(3, 40))):
                assert _gen(dense, p, 8, member=member) == \
                    _gen(paged, p, 8, member=member)
    finally:
        dense.shutdown()
        paged.shutdown()


# ---- aliasing / copy-on-write (slow) ----------------------------------------


@slow
def test_tier0_hit_aliases_pages_with_zero_kv_bytes():
    """A tier-0 prefix hit on a paged engine installs page REFERENCES: the
    alias counter ticks, prefix accounting matches dense exactly, and the
    pool never pays a second copy of the shared span (the headline
    capacity win — dense tier-0 reuse already moved zero bytes, paged
    must not regress that while gaining eviction-surviving donors)."""
    dense, paged = _pair(n_slots=2, prefill_chunk=16)
    try:
        long_p = list(range(3, 3 + 48))       # 3 pages at ps=16
        for eng in (dense, paged):
            _gen(eng, long_p, 8)
        span = paged._page_alloc.pages_for(len(long_p))
        for eng in (dense, paged):
            _gen(eng, long_p + [77], 8)
        m = paged.metrics()
        assert m["kv_page_alias_hits_total"] >= 1
        assert m["prefix_hits_total"] == dense.metrics()["prefix_hits_total"]
        # shared span counted once: well under two full copies
        assert m["kv_pages_allocated"] < 2 * span
        assert m["kv_page_cow_copies_total"] == 0  # chunk-aligned reuse
    finally:
        dense.shutdown()
        paged.shutdown()


@slow
def test_mid_page_reuse_copies_exactly_the_boundary_page():
    """page_size 32 > prefill_chunk 16: a 16-token reuse ends mid-page, so
    the tenant gets a COW clone of the boundary page — and the ORIGINAL
    chain must still decode identically after the tenant writes into its
    copy (the write-isolation half of aliasing)."""
    dense = InferenceEngine(SPEC, seed=0, n_slots=2, prefill_chunk=16)
    paged = InferenceEngine(SPEC, seed=0, n_slots=2, prefill_chunk=16,
                            kv_pages=True, kv_page_size=32)
    try:
        pre = list(range(3, 3 + 20))
        for eng in (dense, paged):
            _gen(eng, pre, 4)
        assert _gen(dense, pre[:17] + [88, 89, 90], 6) == \
            _gen(paged, pre[:17] + [88, 89, 90], 6)
        assert paged.metrics()["kv_page_cow_copies_total"] >= 1
        # the donor prefix decodes unchanged after the COW tenant wrote
        assert _gen(dense, pre + [99], 6) == _gen(paged, pre + [99], 6)
    finally:
        dense.shutdown()
        paged.shutdown()


@slow
def test_prefix_store_restore_under_paging():
    """Churn every slot so the donor's residency is gone, then re-send the
    long prompt: the host prefix store restores through paged_write_rows
    into freshly reserved pages, token-identical to the dense restore."""
    dense, paged = _pair(n_slots=2, prefill_chunk=16, prefix_store="host")
    try:
        long_p = list(range(3, 3 + 64))
        churn = [[100 + i for i in range(40)], [60 + i for i in range(40)],
                 [20 + i for i in range(40)]]
        for eng in (dense, paged):
            _gen(eng, long_p, 4)
            for c in churn:
                _gen(eng, c, 4)
        a = _gen(dense, long_p + [77], 8)
        b = _gen(paged, long_p + [77], 8)
        assert a == b
        assert paged.metrics()["prefix_store_hits_total"] == \
            dense.metrics()["prefix_store_hits_total"]
    finally:
        dense.shutdown()
        paged.shutdown()


# ---- pool exhaustion (slow) -------------------------------------------------


@slow
def test_impossible_span_sheds_at_submit():
    eng = InferenceEngine(SPEC, seed=0, n_slots=4, prefill_chunk=16,
                          kv_pages=True, kv_pool_pages=2)
    try:
        with pytest.raises(QueueFullError, match="page pool"):
            _gen(eng, list(range(3, 60)), 30)
        # a request that fits still serves — the shed is per-span, not a
        # wedged engine
        assert len(_gen(eng, [5, 6, 7], 8)) == 8
    finally:
        eng.shutdown()


@slow
def test_transient_exhaustion_queues_and_drains():
    """8 concurrent streams against an 8-page pool (4 slots): admissions
    wait for live releases instead of OOMing mid-stream, and every stream
    matches its dense twin."""
    paged = InferenceEngine(SPEC, seed=0, n_slots=4, prefill_chunk=16,
                            kv_pages=True, kv_pool_pages=8)
    dense = InferenceEngine(SPEC, seed=0, n_slots=4, prefill_chunk=16)
    try:
        outs_p, outs_d = {}, {}

        def run(eng, i, out):
            p = [3 + i, 4 + i, 5 + i] + list(range(6, 6 + 2 * i))
            out[i] = _gen(eng, p, 10, seed=i)

        ths = ([threading.Thread(target=run, args=(paged, i, outs_p))
                for i in range(8)]
               + [threading.Thread(target=run, args=(dense, i, outs_d))
                  for i in range(8)])
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=300)
        assert len(outs_p) == 8 and outs_p == outs_d
    finally:
        paged.shutdown()
        dense.shutdown()


@slow
def test_self_donor_reclaim_unwedges_own_slot():
    """A slot group's OWN retained donor chain is a page source for its
    own next claim: a donor holding most of the pool must not wedge the
    slot's re-admission (pre-PR-18 this deadlocked — _paged_fits and
    _paged_claim protected the claiming group's donor from reclaim while
    its pages were neither free nor reclaimable, so the admission waited
    forever; surfaced by chaos phase 8). The resubmission still streams
    token for token what the first run streamed."""
    eng = InferenceEngine(SPEC, seed=0, n_slots=1, kv_pages=True,
                          kv_page_size=16, decode_chunk=4)
    try:
        prompt = list(range(3, 33))  # 30 tokens
        # 30 prompt + 48 budget + 1 overshoot = 79 positions -> 5 of the
        # 8 pool pages; the retained donor after the first run holds all
        # 5, leaving only 3 free.
        first = _gen(eng, prompt, 48)
        assert len(first) == 48
        done = {}

        def run():
            done["out"] = _gen(eng, prompt, 48)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        th.join(60)
        assert not th.is_alive(), "re-admission wedged on own donor"
        assert done["out"] == first
    finally:
        eng.shutdown()
