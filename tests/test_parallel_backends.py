"""Parallel non-streaming concatenate parity
(/root/reference/tests/test_parallel_backends.py): exact joined content,
summed usage, partial failure, think stripping."""

import pytest

from quorum_tpu.backends import BackendError, FakeBackend
from tests.conftest import make_client, two_backend_parallel_config

AUTH = {"Authorization": "Bearer sk-test"}


async def test_concatenate_joins_and_sums_usage():
    cfg = two_backend_parallel_config(separator="\nSEP\n")
    f1 = FakeBackend(
        "LLM1", text="one", usage={"prompt_tokens": 10, "completion_tokens": 5, "total_tokens": 15}
    )
    f2 = FakeBackend(
        "LLM2", text="two", usage={"prompt_tokens": 7, "completion_tokens": 3, "total_tokens": 10}
    )
    async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
        r = await client.post("/chat/completions", json={"model": "m", "messages": []}, headers=AUTH)
    assert r.status_code == 200
    data = r.json()
    assert data["choices"][0]["message"]["content"] == "one\nSEP\ntwo"
    assert data["usage"] == {
        "prompt_tokens": 17,
        "completion_tokens": 8,
        "total_tokens": 25,
    }
    assert data["object"] == "chat.completion"
    assert data["choices"][0]["finish_reason"] == "stop"


async def test_partial_failure_serves_survivors():
    cfg = two_backend_parallel_config(separator="|")
    f1 = FakeBackend("LLM1", fail_with=BackendError("down", status_code=500))
    f2 = FakeBackend("LLM2", text="survivor")
    async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.status_code == 200
    assert r.json()["choices"][0]["message"]["content"] == "survivor"


async def test_all_fail_500():
    cfg = two_backend_parallel_config()
    f1 = FakeBackend("LLM1", fail_with=BackendError("e1", status_code=500))
    f2 = FakeBackend("LLM2", fail_with=BackendError("e2", status_code=500))
    async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.status_code == 500
    err = r.json()["error"]
    assert "All backends failed" in err["message"]
    assert "e1" in err["message"]  # first error


async def test_hide_final_think_strips_tags():
    cfg = two_backend_parallel_config(separator="|", hide_final_think=True)
    f1 = FakeBackend("LLM1", text="<think>secret</think>clean1")
    f2 = FakeBackend("LLM2", text="clean2")
    async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.json()["choices"][0]["message"]["content"] == "clean1|clean2"


async def test_think_preserved_when_disabled():
    cfg = two_backend_parallel_config(separator="|", hide_final_think=False)
    f1 = FakeBackend("LLM1", text="<think>x</think>y")
    f2 = FakeBackend("LLM2", text="z")
    async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.json()["choices"][0]["message"]["content"] == "<think>x</think>y|z"


async def test_response_reuses_first_success_identity():
    cfg = two_backend_parallel_config(separator="|")
    f1 = FakeBackend("LLM1", text="a")
    f2 = FakeBackend("LLM2", text="b")
    async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    data = r.json()
    # id/model/created come from the first successful backend response
    # (oai_proxy.py:1315-1335)
    first = await f1.complete({"model": "m"}, {}, 5)
    assert data["model"] == first.body["model"]
