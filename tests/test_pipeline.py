"""Pipeline parallelism (parallel/pipeline.py): the staged schedule must be
an exact re-scheduling of the dense forward — same math, stage hand-offs over
ppermute — and trainable end-to-end on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quorum_tpu.models import init_params, resolve_spec
from quorum_tpu.models.transformer import forward_logits
from quorum_tpu.parallel import (
    MeshConfig,
    make_mesh,
    make_pp_train_step,
    pipeline_forward_logits,
    pp_train_init,
    shard_pytree_pp,
)

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

SPEC = resolve_spec("llama-tiny", {"n_layers": "4", "max_seq": "64"})


def test_pipeline_matches_dense_forward():
    mesh = make_mesh(MeshConfig(pp=4), jax.devices()[:4])
    params = init_params(SPEC, seed=0)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0,
                                SPEC.vocab_size)
    ref = np.asarray(forward_logits(params, SPEC, tokens), np.float32)
    staged = shard_pytree_pp(mesh, params)
    got = np.asarray(
        jax.jit(lambda p, t: pipeline_forward_logits(p, SPEC, t, mesh,
                                                     n_micro=2))(staged, tokens),
        np.float32,
    )
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-2)


def test_pipeline_composes_with_dp():
    mesh = make_mesh(MeshConfig(dp=2, pp=2), jax.devices()[:4])
    params = init_params(SPEC, seed=1)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                                SPEC.vocab_size)
    ref = np.asarray(forward_logits(params, SPEC, tokens), np.float32)
    staged = shard_pytree_pp(mesh, params)
    got = np.asarray(
        pipeline_forward_logits(staged, SPEC, tokens, mesh, n_micro=4),
        np.float32,
    )
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-2)


def test_pipeline_moe_runs():
    spec = resolve_spec("mixtral-tiny", {"max_seq": "64"})
    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    params = shard_pytree_pp(mesh, init_params(spec, seed=0))
    tokens = jnp.ones((2, 8), jnp.int32)
    out = pipeline_forward_logits(params, spec, tokens, mesh, n_micro=2)
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_pp_train_step_decreases_loss():
    mesh = make_mesh(MeshConfig(dp=2, pp=2), jax.devices()[:4])
    state = pp_train_init(SPEC, mesh, seed=0)
    step = make_pp_train_step(SPEC, mesh, n_micro=2)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 1,
                                SPEC.vocab_size)
    state, loss0 = step(state, tokens)
    for _ in range(4):
        state, loss = step(state, tokens)
    assert float(loss) < float(loss0), (float(loss0), float(loss))
    assert np.isfinite(float(loss))


def test_pp_loss_matches_dense_loss():
    """The pipelined loss equals the dense trainer's loss on the same
    params/tokens (same math, different schedule)."""
    from quorum_tpu.parallel.pipeline import pp_loss_fn
    from quorum_tpu.training.trainer import loss_fn

    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    params = init_params(SPEC, seed=3)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 24), 1,
                                SPEC.vocab_size)
    dense = float(loss_fn(params, SPEC, tokens, remat=False))
    staged = shard_pytree_pp(mesh, params)
    piped = float(pp_loss_fn(staged, SPEC, tokens, mesh, 2, remat=False))
    assert abs(dense - piped) / max(abs(dense), 1e-6) < 2e-2


def test_pp_mesh_validation():
    mesh = make_mesh(MeshConfig(pp=2, tp=2), jax.devices()[:4])
    params = init_params(SPEC, seed=0)
    with pytest.raises(ValueError, match="dp only"):
        pipeline_forward_logits(params, SPEC, jnp.ones((2, 8), jnp.int32),
                                mesh, n_micro=2)
    mesh3 = make_mesh(MeshConfig(pp=3), jax.devices()[:3])
    with pytest.raises(ValueError, match="divide"):
        pipeline_forward_logits(params, SPEC, jnp.ones((2, 8), jnp.int32),
                                mesh3, n_micro=2)
