"""Pipeline-staged decode (``pp=K``, ISSUE 14): serve models bigger than
one device group's HBM.

Fast tier: the config-rejection matrix (every invalid knob combination
rejects at config time with the reason — never at first dispatch), the
bit-for-bit parity of the staged chunk/megachunk programs against
``decode_chunk``/``decode_loop``, a pp=2 engine pinned token-for-token
against a single-device engine (with the staged program families under
their own budget keys and the per-stage occupancy gauge live), and the
synthetic HBM-budget acceptance: a model whose weight+KV footprint
exceeds one group's budget still serves, because no stage holds more
than its layer shard.

Slow tier: disagg=1+2&pp=2 (the handoff feeding stage 0 of a staged
decode group) and the ring-full dispatch-counter acceptance at
``decode_pipeline=2 × decode_loop=2``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quorum_tpu import observability as obs
from quorum_tpu.analysis import budget
from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.ops.sampling import SamplerConfig, sample_token_rows
from quorum_tpu.parallel.mesh import (
    MeshConfig,
    disagg_meshes,
    group_mesh_configs,
    make_mesh,
    single_device_mesh,
)

TINY = resolve_spec("llama-tiny", {"n_kv_heads": "4"})
SAMPLED = SamplerConfig(temperature=0.8, top_p=0.9)
GREEDY = SamplerConfig(temperature=0.0)


def _gen(eng, prompt, seed=0, n=8, sampler=SAMPLED, **kw):
    return eng.generate(prompt, max_new_tokens=n, sampler=sampler,
                        seed=seed, **kw).token_ids


# ---- fast: the config-rejection matrix -------------------------------------


def test_group_mesh_config_rejections():
    """Every invalid disagg-side factorization fails in
    group_mesh_configs with the arithmetic, at config time."""
    for kw, frag in [
        (dict(tp=3), "does not factor"),        # non-divisible tp vs group
        (dict(sp=3), "does not factor"),        # sp must divide prefill
        (dict(pp=3), "does not factor"),        # pp must divide decode
        (dict(tp=0), ">= 1"),
        (dict(sp=0), ">= 1"),
        (dict(pp=0), ">= 1"),
    ]:
        with pytest.raises(ValueError, match=frag):
            group_mesh_configs(4, 4, **kw)
    # pp shares the decode group with a >1 tp residue: staged decode runs
    # tp=1 within each stage (prefill side factors fine here: 2 = 1x2)
    with pytest.raises(ValueError, match="tp=1 within each stage"):
        group_mesh_configs(2, 4, pp=2, tp=2)
    # the factoring identities that must pass
    pre, dec = group_mesh_configs(4, 4)
    assert (pre.tp, dec.tp) == (4, 4)  # no knobs = whole-group tp
    pre, dec = group_mesh_configs(4, 4, tp=4)
    assert (pre.sp, pre.tp, dec.pp, dec.tp) == (1, 4, 1, 4)
    pre, dec = group_mesh_configs(4, 2, sp=2, pp=2)
    assert (pre.sp, pre.tp, dec.pp, dec.tp) == (2, 2, 2, 1)


def test_engine_pp_rejections():
    """The engine-side matrix: pp vs layer count / slot count, and the
    combinations the staged schedule cannot express — each rejects at
    construction with a one-line actionable error."""
    mesh_pp = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    with pytest.raises(ValueError, match="does not divide n_layers"):
        InferenceEngine(resolve_spec("llama-tiny", {"n_layers": "3"}),
                        make_mesh(MeshConfig(pp=2), jax.devices()[:2]))
    with pytest.raises(ValueError, match="does not divide slots"):
        InferenceEngine(TINY, mesh_pp, n_slots=3)
    with pytest.raises(ValueError, match="zero_drain"):
        InferenceEngine(TINY, mesh_pp, zero_drain=True, prefill_chunk=16)
    with pytest.raises(ValueError, match="members/ensemble"):
        InferenceEngine(TINY, mesh_pp, members=2)
    with pytest.raises(ValueError, match="members/ensemble"):
        InferenceEngine(TINY, mesh_pp, ensemble=2)
    with pytest.raises(ValueError, match="spec_decode"):
        InferenceEngine(TINY, mesh_pp, spec_decode=4)
    with pytest.raises(ValueError, match="sp>1"):
        InferenceEngine(TINY, make_mesh(MeshConfig(pp=2, sp=2),
                                        jax.devices()[:4]))
    # colocated pp beside tp/dp: the staged shard_map partitions over pp
    # only — a tp/dp axis would be silently replicated per stage, the
    # exact HBM blow-up pp exists to avoid (the disagg side pins the same
    # contract via group_mesh_configs)
    with pytest.raises(ValueError, match="tp=1/dp=1 within each stage"):
        InferenceEngine(TINY, make_mesh(MeshConfig(pp=2, tp=2),
                                        jax.devices()[:4]))
    with pytest.raises(ValueError, match="tp=1/dp=1 within each stage"):
        InferenceEngine(TINY, make_mesh(MeshConfig(pp=2, dp=2),
                                        jax.devices()[:4]))


def test_engine_disagg_sharding_rejections():
    """disagg-side engine rejections: sp in the DECODE group, and a
    prefill-group sp that does not divide max_seq."""
    pm, dm = disagg_meshes(1, 2)
    sp_decode = make_mesh(MeshConfig(sp=2), jax.devices()[1:3])
    with pytest.raises(ValueError, match="PREFILL group"):
        InferenceEngine(TINY, sp_decode,
                        prefill_mesh=make_mesh(MeshConfig(tp=1),
                                               jax.devices()[:1]),
                        prefill_chunk=16)
    # sp=3 cannot shard a 128-position staging cache evenly
    pm2, dm2 = disagg_meshes(3, 1, sp=3)
    with pytest.raises(ValueError, match="does not divide max_seq"):
        InferenceEngine(TINY, dm2, prefill_mesh=pm2, prefill_chunk=16)


def test_url_pp_rejections():
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    def build(url):
        return TpuBackend.from_spec(
            BackendSpec(name="t", url=url, model="m"))

    for url, frag in [
        ("tpu://llama-tiny?pp=2&zero_drain=1", "zero_drain"),
        ("tpu://llama-tiny?n_layers=3&pp=2", "does not divide n_layers"),
        ("tpu://llama-tiny?disagg=2+4&pp=2", "tp=1 within each stage"),
        ("tpu://llama-tiny?disagg=2+2&dp=2", "dp= does not compose"),
        ("tpu://llama-tiny?pp=2&sp=2", "sp>1"),
        ("tpu://llama-tiny?pp=2&tp=2", "tp=1/dp=1 within each stage"),
        ("tpu://llama-tiny?pp=2&dp=2", "tp=1/dp=1 within each stage"),
        ("tpu://llama-tiny?pp=2&spec_decode=4", "spec_decode"),
    ]:
        with pytest.raises(ValueError, match=frag):
            build(url)


# ---- fast: staged program parity against decode_chunk/decode_loop ----------


@pytest.fixture(scope="module")
def parity_setup():
    from quorum_tpu.models.init import init_params_sharded
    from quorum_tpu.models.transformer import init_cache
    from quorum_tpu.parallel.sharding import kv_cache_sharding

    spec = resolve_spec("llama-tiny",
                        {"n_kv_heads": "4", "n_layers": "4",
                         "max_seq": "64"})
    b = 4
    mesh_pp = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    mesh_one = single_device_mesh()

    def build(mesh):
        params = init_params_sharded(spec, mesh, seed=0)
        sh = kv_cache_sharding(mesh, spec.n_kv_heads, batch=b)
        ck, cv = jax.jit(lambda: init_cache(spec, batch=b),
                         out_shardings=(sh, sh))()
        return params, ck, cv

    def sample_fn(logits, lv, carry):
        # An engine-shaped sampler: penalties on the carry counts, a
        # per-row RNG chain split once per token, and mixed aux leaves
        # (a per-row logprob record + a per-step scalar).
        keys, counts = carry
        adj = logits - 0.1 * counts
        split = jax.vmap(jax.random.split)(keys)
        nxt = sample_token_rows(adj, split[:, 1],
                                jnp.full((b,), 0.8, jnp.float32),
                                jnp.full((b,), 0.9, jnp.float32),
                                jnp.zeros((b,), jnp.int32))
        counts = counts.at[jnp.arange(b), nxt].add(lv.astype(jnp.int32))
        lp = jax.nn.log_softmax(adj)
        s_lp = jnp.take_along_axis(lp, nxt[:, None], 1)[:, 0]
        n_live = jnp.sum(lv.astype(jnp.int32))
        return nxt, (split[:, 0], counts), (s_lp, n_live)

    state = dict(
        token=jnp.array([3, 4, 5, 6], jnp.int32),
        lengths=jnp.array([1, 2, 1, 3], jnp.int32),
        live=jnp.array([True, True, False, True]),
        budget=jnp.array([8, 3, 5, 8], jnp.int32),
        eos=jnp.array([-1, -1, -1, 7], jnp.int32),
        keys=jax.vmap(jax.random.PRNGKey)(jnp.arange(b, dtype=jnp.uint32)),
        counts=jnp.zeros((b, spec.vocab_size), jnp.int32),
    )
    return spec, mesh_pp, build, sample_fn, state


def _trees_equal(a, b):
    return all(
        np.array_equal(np.asarray(x, np.float32), np.asarray(y, np.float32))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def test_staged_chunk_bit_for_bit(parity_setup):
    """staged_decode_chunk == decode_chunk on every output leaf: tokens,
    n_valid, live/budget finish state, BOTH cache halves, lengths, the
    sampler carry (per-row RNG chains split exactly once per token), and
    the mixed-shape aux buffers — including a mid-chunk EOS row, a
    budget-exhausted row, and a dead-at-entry row."""
    from quorum_tpu.models.transformer import decode_chunk
    from quorum_tpu.parallel.pipeline import staged_decode_chunk

    spec, mesh_pp, build, sample_fn, st = parity_setup
    p1, ck1, cv1 = build(single_device_mesh())
    ref = jax.jit(lambda ck, cv, k, c: decode_chunk(
        p1, spec, 4, st["token"], st["lengths"], st["live"], st["budget"],
        st["eos"], ck, cv, sample_fn, (k, c), history=32))(
        ck1, cv1, st["keys"], st["counts"])
    p2, ck2, cv2 = build(mesh_pp)
    got = jax.jit(lambda ck, cv, k, c: staged_decode_chunk(
        p2, spec, mesh_pp, 4, st["token"], st["lengths"], st["live"],
        st["budget"], st["eos"], ck, cv, sample_fn, (k, c), history=32))(
        ck2, cv2, st["keys"], st["counts"])
    assert _trees_equal(ref, got)


def test_staged_loop_bit_for_bit(parity_setup):
    """staged_decode_loop == decode_loop (the megachunk contract: leading
    per-chunk axis, all-rows-finished early exit, carry passthrough)."""
    from quorum_tpu.models.transformer import decode_loop
    from quorum_tpu.parallel.pipeline import staged_decode_loop

    spec, mesh_pp, build, sample_fn, st = parity_setup
    p1, ck1, cv1 = build(single_device_mesh())
    ref = jax.jit(lambda ck, cv, k, c: decode_loop(
        p1, spec, 2, 4, st["token"], st["lengths"], st["live"],
        st["budget"], st["eos"], ck, cv, sample_fn, (k, c), history=32))(
        ck1, cv1, st["keys"], st["counts"])
    p2, ck2, cv2 = build(mesh_pp)
    got = jax.jit(lambda ck, cv, k, c: staged_decode_loop(
        p2, spec, mesh_pp, 2, 4, st["token"], st["lengths"], st["live"],
        st["budget"], st["eos"], ck, cv, sample_fn, (k, c), history=32))(
        ck2, cv2, st["keys"], st["counts"])
    assert _trees_equal(ref, got)


# ---- fast: pp=2 engine pinned against the single-device engine -------------


@pytest.fixture(scope="module")
def pp_engines():
    kw = dict(decode_chunk=4, n_slots=2, decode_pipeline=2, decode_loop=2,
              prefill_chunk=16, seed=9500)
    eng_1 = InferenceEngine(TINY, **kw)
    eng_pp = InferenceEngine(TINY, make_mesh(MeshConfig(pp=2),
                                             jax.devices()[:2]), **kw)
    yield eng_1, eng_pp
    eng_1.shutdown()
    eng_pp.shutdown()


def test_pp_engine_token_for_token(pp_engines):
    """pp=2 serves greedy and sampled streams token-for-token identical
    to the single-device engine, under the suite-wide transfer guard
    (zero new blocking syncs on the token critical path)."""
    eng_1, eng_pp = pp_engines
    assert eng_pp.decode_pp == 2
    assert eng_pp.transfer_guard == "disallow"  # conftest's runtime sentinel
    for prompt, sampler, seed in [([3, 4, 5], GREEDY, 0),
                                  ([7, 8, 9], SAMPLED, 11)]:
        assert (_gen(eng_pp, prompt, seed=seed, sampler=sampler)
                == _gen(eng_1, prompt, seed=seed, sampler=sampler))


def test_pp_program_families_and_occupancy(pp_engines):
    """Staged engines compile ONLY "pp"-tagged decode programs (their own
    compile_budget.json families — never a cache entry shared with the
    unstaged variants), the unstaged engine never compiles one, and the
    per-stage occupancy gauge carries stage-labeled series."""
    eng_1, eng_pp = pp_engines
    _gen(eng_pp, [5, 6], seed=1)
    fams_pp = budget.decode_families(eng_pp._decode_cache)
    assert fams_pp and fams_pp <= {"pp_plain", "pp_loop"}, fams_pp
    fams_1 = budget.decode_families(eng_1._decode_cache)
    assert not any(f.startswith("pp") for f in fams_1), fams_1
    assert all(k[0] == "pp" for k in eng_pp._decode_cache)
    # stage-labeled occupancy series exist (values are last-writer-wins)
    lines = obs.DECODE_STAGE_OCCUPANCY.expose()
    assert any('stage="0"' in ln for ln in lines), lines
    assert any('stage="1"' in ln for ln in lines), lines


def test_pp_engine_ring_stays_full(pp_engines):
    """Dispatch-counter acceptance: the staged engine keeps the
    decode_pipeline=2 × decode_loop=2 ring full — dispatches overlap
    (n_overlapped grows) and megachunks fuse (executed chunk segments
    outnumber dispatches)."""
    _, eng_pp = pp_engines
    over0, chunks0, loops0 = (eng_pp.n_overlapped, eng_pp.n_decode_chunks,
                              eng_pp.n_loop_chunks)
    _gen(eng_pp, [3, 4, 5], seed=7, n=24)
    _gen(eng_pp, [3, 4, 5], seed=7, n=24)  # warm programs: depth-2 ring
    assert eng_pp.n_overlapped > over0
    assert eng_pp.n_loop_chunks - loops0 > eng_pp.n_decode_chunks - chunks0


# ---- fast: the synthetic HBM-budget acceptance ------------------------------


def test_pp_serves_model_exceeding_one_group_budget():
    """The tentpole claim, enforced synthetically: a model+cache footprint
    BIGGER than one group's (synthetic) HBM budget serves on a pp=2 staged
    mesh because every stage holds only its L/pp layer shard + that
    shard's KV — max per-device bytes stays under the budget the total
    breaks."""
    spec = resolve_spec("llama-tiny", {"n_kv_heads": "4", "n_layers": "8"})
    mesh_pp = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    eng = InferenceEngine(spec, mesh_pp, decode_chunk=4, n_slots=2,
                          prefill_chunk=16, seed=9510)
    try:
        arrs = jax.tree.leaves((eng.params, eng._ck, eng._cv))
        total = sum(x.nbytes for x in arrs)
        per_dev: dict = {}
        for leaf in arrs:
            for sh in leaf.addressable_shards:
                per_dev[sh.device] = (per_dev.get(sh.device, 0)
                                      + sh.data.nbytes)
        assert len(per_dev) == 2
        worst = max(per_dev.values())
        # One group's synthetic HBM budget: big enough for any single
        # stage, too small for the whole model — the configuration an
        # unsharded group cannot hold but the staged engine serves.
        group_budget = int(total * 0.75)
        assert total > group_budget, (total, group_budget)
        assert worst <= group_budget, (worst, group_budget, total)
        out = _gen(eng, [3, 4, 5], seed=2, n=8)
        assert len(out) == 8
    finally:
        eng.shutdown()


# ---- slow: constrained decoding through the staged grammar path ------------


@pytest.mark.slow
def test_pp_constrained_pin():
    """response_format JSON mode on a pp=2 staged engine equals the
    single-device engine byte for byte — the grammar mask and DFA advance
    ride the LAST stage's sampler inside the staged tick scan (the
    pp_loop_dfa/pp_dfa program families)."""
    import asyncio

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    def build(url):
        return TpuBackend.from_spec(BackendSpec(name="t", url=url,
                                                model="m"))

    opts = ("n_kv_heads=4&seed=9530&decode_pipeline=2&decode_loop=2"
            "&prefill_chunk=16&decode_chunk=4&slots=2")
    b_pp = build(f"tpu://llama-tiny?{opts}&pp=2")
    b_1 = build(f"tpu://llama-tiny?{opts}")
    body = {"model": "m", "max_tokens": 24, "temperature": 0.0, "seed": 3,
            "messages": [{"role": "user", "content": "json please"}],
            "response_format": {"type": "json_object"}}

    async def run_one(b):
        res = await b.complete(dict(body), {}, timeout=300)
        return res.body["choices"][0]["message"]["content"]

    assert asyncio.run(run_one(b_pp)) == asyncio.run(run_one(b_1))
    assert b_pp.engine.n_constrained >= 1
    fams = budget.decode_families(b_pp.engine._decode_cache)
    assert any("dfa" in f and f.startswith("pp") for f in fams), fams


# ---- slow: disagg + staged decode group ------------------------------------


@pytest.mark.slow
def test_disagg_pp_staged_decode_group_pin():
    """disagg=1+2&pp=2: the chunk-granular handoff feeds stage 0 of a
    pipeline-staged decode group (resharding to the stage-sharded cache
    on the fly) and the stream equals the single-device engine's token
    for token."""
    kw = dict(decode_chunk=4, n_slots=2, decode_pipeline=2, decode_loop=2,
              prefill_chunk=16, seed=9520)
    pm, dm = disagg_meshes(1, 2, pp=2)
    eng_1 = InferenceEngine(TINY, **kw)
    eng_dp = InferenceEngine(TINY, dm, prefill_mesh=pm, **kw)
    try:
        long_p = [(3 + 5 * i) % 500 for i in range(40)]
        for prompt, sampler, seed in [([3, 4, 5], GREEDY, 0),
                                      ([7, 8, 9], SAMPLED, 11),
                                      (long_p, SAMPLED, 3)]:
            assert (_gen(eng_dp, prompt, seed=seed, sampler=sampler)
                    == _gen(eng_1, prompt, seed=seed, sampler=sampler))
        assert eng_dp.n_kv_handoffs > 0
        assert eng_dp.decode_pp == 2
        fams = budget.decode_families(eng_dp._decode_cache)
        assert fams and fams <= {"pp_plain", "pp_loop"}, fams
    finally:
        eng_1.shutdown()
        eng_dp.shutdown()
