"""Smoke over the prefix-store microbench (``make prefix-bench``).

Runs the same entry point the Makefile target runs, at a budget small
enough for the fast tier, and pins the ISSUE-3 acceptance behavior: under
slot churn (more conversations than slots) follow-up turns hit the host
store, prefill only the tail, and the sampled output is byte-identical to
the store-less engine's cold full prefill.
"""

from scripts.prefix_bench import run

import pytest


def test_prefix_bench_counters():
    m = run(conversations=3, slots=1, turns=2, new_tokens=5, chunk=16)
    # Every turn-2 conversation finds its slot reclaimed; each must have
    # restored its history from the host store instead of re-prefilling.
    assert m["on_store_hits"] >= m["conversations"]
    assert m["on_store_restored_tokens"] >= 16 * m["conversations"]
    assert m["prefill_tokens_saved_by_store"] > 0
    assert m["on_prefill_tokens"] < m["off_prefill_tokens"]
    assert m["on_restore_ms_mean"] > 0.0
    # reuse is a scheduling optimization, never a semantic change
    assert m["tokens_match"] is True


def test_prefix_bench_rejects_churnless_shape():
    with pytest.raises(ValueError, match="exceed"):
        run(conversations=2, slots=2, turns=1)
