"""Automatic prefix caching (engine/engine.py): a request whose prompt
prefix is already resident in a free slot's KV cache must admit into that
slot, prefill only the suffix, and generate EXACTLY what a cache-less engine
generates — reuse is a scheduling optimization, never a semantic change.

Reuse lengths are aligned DOWN to a prefill_chunk multiple: segment offsets
must stay chunk-aligned (chunk divides max_seq) or the final segment's
bucket-padded cache write could cross max_seq and silently corrupt rows.
"""

import jax

from quorum_tpu.engine.engine import MIN_PREFIX_REUSE, InferenceEngine
from quorum_tpu.models import resolve_spec
from quorum_tpu.ops.sampling import SamplerConfig

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

SPEC = resolve_spec("llama-tiny", {"max_seq": "128"})
GREEDY = SamplerConfig(temperature=0.0)
CHUNK = 16  # small alignment unit so short test prompts exercise reuse


def _prompt(n, base=3):
    return [(base + i * 7) % (SPEC.vocab_size - 1) + 1 for i in range(n)]


def _engines():
    eng = InferenceEngine(SPEC, decode_chunk=4, prefill_chunk=CHUNK)
    ref = InferenceEngine(SPEC, decode_chunk=4, prefill_chunk=CHUNK,
                          prefix_cache=False)
    return eng, ref


def test_repeat_prompt_reuses_prefix_and_matches():
    eng, ref = _engines()
    p = _prompt(24)
    first = eng.generate(p, max_new_tokens=6, sampler=GREEDY, seed=5).token_ids
    assert eng.prefix_hits == 0
    second = eng.generate(p, max_new_tokens=6, sampler=GREEDY, seed=5).token_ids
    assert eng.prefix_hits == 1
    # lcp 24 caps at len(p)-1 = 23, aligns down to the chunk multiple 16
    assert eng.prefix_tokens_saved == 16
    baseline = ref.generate(p, max_new_tokens=6, sampler=GREEDY, seed=5).token_ids
    assert first == baseline
    assert second == baseline, "prefix reuse changed the generation"


def test_multi_turn_history_reuses_prefix():
    eng, ref = _engines()
    turn1 = _prompt(20)
    gen1 = eng.generate(turn1, max_new_tokens=5, sampler=GREEDY, seed=1).token_ids
    # next turn re-sends history + the "assistant reply" + new user tokens
    turn2 = turn1 + gen1 + _prompt(6, base=100)
    gen2 = eng.generate(turn2, max_new_tokens=5, sampler=GREEDY, seed=2).token_ids
    assert eng.prefix_hits == 1
    assert eng.prefix_tokens_saved >= CHUNK
    baseline = ref.generate(turn2, max_new_tokens=5, sampler=GREEDY,
                            seed=2).token_ids
    assert gen2 == baseline


def test_reuse_near_max_seq_is_exact():
    """End-game regression: a reused prefix plus a suffix that fills the
    context almost to max_seq — the final segment's bucket write must not
    cross max_seq (chunk alignment invariant)."""
    eng = InferenceEngine(SPEC, decode_chunk=2, prefill_chunk=32)
    ref = InferenceEngine(SPEC, decode_chunk=2, prefill_chunk=32,
                          prefix_cache=False)
    first = _prompt(100)
    gen1 = eng.generate(first, max_new_tokens=4, sampler=GREEDY, seed=3).token_ids
    long2 = (first + gen1 + _prompt(40, base=77))[:127]
    got = eng.generate(long2, max_new_tokens=1, sampler=GREEDY, seed=4).token_ids
    assert eng.prefix_hits == 1
    assert eng.prefix_tokens_saved == 96  # lcp 103 aligned down to 96
    baseline = ref.generate(long2, max_new_tokens=1, sampler=GREEDY,
                            seed=4).token_ids
    assert got == baseline


def test_disjoint_prompt_no_reuse():
    eng, _ = _engines()
    eng.generate(_prompt(24), max_new_tokens=4, sampler=GREEDY).token_ids
    eng.generate(_prompt(24, base=200), max_new_tokens=4,
                 sampler=GREEDY).token_ids
    assert eng.prefix_hits == 0


def test_short_match_below_threshold_no_reuse():
    eng, _ = _engines()
    p = _prompt(MIN_PREFIX_REUSE - 4)
    eng.generate(p, max_new_tokens=4, sampler=GREEDY).token_ids
    eng.generate(p, max_new_tokens=4, sampler=GREEDY).token_ids
    assert eng.prefix_hits == 0


def test_prefix_cache_knob_and_metrics():
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    off = TpuBackend.from_spec(BackendSpec(
        name="NC", url="tpu://llama-tiny?prefix_cache=0&max_seq=64&seed=11",
        model="m"))
    assert off.engine.prefix_cache is False
    on = TpuBackend.from_spec(BackendSpec(
        name="C", url="tpu://llama-tiny?max_seq=64&seed=12", model="m"))
    assert on.engine.prefix_cache is True
    m = on.engine.metrics()
    assert m["prefix_hits_total"] == 0
    assert m["prefix_tokens_saved_total"] == 0
    # an explicit opt-out from a later backend sharing the engine wins
    shared_off = TpuBackend.from_spec(BackendSpec(
        name="C2", url="tpu://llama-tiny?prefix_cache=0&max_seq=64&seed=12",
        model="m"))
    assert shared_off.engine is on.engine
    assert on.engine.prefix_cache is False


def test_no_match_prefers_empty_slot():
    """A disjoint request must not evict a long resident prefix when an
    emptier slot is free (tie-break on shortest resident)."""
    eng = InferenceEngine(SPEC, decode_chunk=4, prefill_chunk=CHUNK, n_slots=2)
    conv = _prompt(40)
    eng.generate(conv, max_new_tokens=4, sampler=GREEDY, seed=1)
    # unrelated request: lcp 0 everywhere → should land on the empty slot
    eng.generate(_prompt(20, base=300), max_new_tokens=4, sampler=GREEDY)
    # the conversation's prefix must still be reusable
    eng.generate(conv + _prompt(4, base=50), max_new_tokens=4,
                 sampler=GREEDY, seed=2)
    assert eng.prefix_hits == 1
    assert eng.prefix_tokens_saved >= 32


def test_invalid_prefix_cache_value_rejected():
    import pytest as _pytest

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    with _pytest.raises(ValueError, match="prefix_cache"):
        TpuBackend.from_spec(BackendSpec(
            name="X", url="tpu://llama-tiny?prefix_cache=off", model="m"))
