"""Tiered KV prefix store (quorum_tpu/cache/ + the engine's snapshot/
restore hooks): host-RAM retention of decoded prefixes beyond the slots.

The contract: restoring a stored prefix is a scheduling optimization,
never a semantic change — under slot churn a follow-up turn that restores
from the host store generates token-for-token what a cold full prefill
generates. Eviction honors the byte budget; the store holds the cache's
native representation (kv_quant=int8 halves host bytes); members>1 is a
config error, not silently-wrong output.
"""

import numpy as np

from quorum_tpu.cache.prefix_store import PrefixStore
from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models import resolve_spec
from quorum_tpu.ops.sampling import SamplerConfig

import pytest

SPEC = resolve_spec("llama-tiny", {"max_seq": "128"})
GREEDY = SamplerConfig(temperature=0.0)
CHUNK = 16  # small alignment unit so short test prompts exercise the tier


def _prompt(n, base=3):
    return [(base + i * 7) % (SPEC.vocab_size - 1) + 1 for i in range(n)]


# ---- store unit tests (no jax, no engine) ----------------------------------


def _payload(tag: int, nbytes: int = 64):
    return [np.full((nbytes,), tag % 127, np.int8)]


def test_store_longest_match_walks_chunk_chain():
    s = PrefixStore(chunk_tokens=4, max_bytes=1 << 20)
    toks = list(range(12))
    assert s.insert(toks, 0, [_payload(0), _payload(1), _payload(2)])
    n, chunks = s.longest_match(toks + [99, 98])
    assert n == 12 and len(chunks) == 3
    # diverging suffix matches only the shared chunks
    n, chunks = s.longest_match(toks[:8] + [7, 7, 7, 7])
    assert n == 8 and len(chunks) == 2
    # a partial trailing chunk never matches (chunk granularity)
    n, _ = s.longest_match(toks[:10])
    assert n == 8
    assert s.covered(toks) == 12


def test_store_shared_prefixes_share_storage():
    s = PrefixStore(chunk_tokens=4, max_bytes=1 << 20)
    a = list(range(8))
    s.insert(a, 0, [_payload(0), _payload(1)])
    held = s.bytes_held
    # same chain re-inserted: no growth, still one copy
    assert s.insert(a, 0, [_payload(0), _payload(1)])
    assert s.bytes_held == held
    # an extension stores only its new chunk
    s.insert(a + [50, 51, 52, 53], 8, [_payload(2)])
    assert s.n_entries == 3


def test_store_eviction_honors_byte_budget_lru():
    s = PrefixStore(chunk_tokens=2, max_bytes=200)
    for i in range(5):  # 5 disjoint 64-byte chains
        s.insert([100 + 2 * i, 101 + 2 * i], 0, [_payload(i)])
    assert s.bytes_held <= 200
    assert s.n_evictions >= 2
    # the oldest chains evicted first
    assert s.longest_match([100, 101])[0] == 0
    assert s.longest_match([108, 109])[0] == 2
    # a hit refreshes recency: touch chain 2, insert another, 3 evicts next
    s.longest_match([104, 105])
    s.insert([200, 201], 0, [_payload(9)])
    assert s.longest_match([104, 105])[0] == 2


def test_store_extension_insert_keeps_own_prefix_under_pressure():
    """An over-budget insert of a chain EXTENSION must evict other chains
    (or its own tail), never the prefix chunks the new suffix depends on:
    the whole chain — validated prefix included — is LRU-refreshed
    root-newest, so eviction cannot strand unmatchable suffix bytes."""
    s = PrefixStore(chunk_tokens=2, max_bytes=200)  # fits 3×64-byte chunks
    x = [1, 2, 3, 4]
    assert s.insert(x, 0, [_payload(0), _payload(1)])
    assert s.insert([50, 51], 0, [_payload(2)])  # unrelated, now LRU-oldest
    assert s.bytes_held <= 200
    # extending X breaches the budget: the unrelated chain evicts, X stays
    # matchable root-to-leaf
    assert s.insert(x + [5, 6], 4, [_payload(3)])
    assert s.longest_match(x + [5, 6])[0] == 6
    assert s.longest_match([50, 51])[0] == 0
    assert s.bytes_held <= 200


def test_store_insert_refuses_broken_chain():
    s = PrefixStore(chunk_tokens=2, max_bytes=1 << 20)
    toks = [1, 2, 3, 4]
    with pytest.raises(ValueError, match="chunk-aligned"):
        s.insert(toks, 1, [_payload(0)])
    # offset past a never-stored prefix: refused, not a gapped chain
    assert s.insert(toks, 2, [_payload(1)]) is False
    assert s.covered(toks) == 0


# ---- engine-level tests (slow tier, like test_prefix_cache.py) -------------

# NOTE: not module-level pytestmark — the store unit tests above stay in the
# fast tier; only the engine-scale tests below are slow.
slow = pytest.mark.slow


def _store_engine(**kw):
    return InferenceEngine(SPEC, decode_chunk=4, prefill_chunk=CHUNK,
                           n_slots=1, prefix_store="host", **kw)


@slow
def test_churn_restore_matches_cold_full_prefill():
    """The scenario slot-resident caching loses (ISSUE 3 acceptance): the
    conversation's slot is reclaimed by another request; the follow-up turn
    restores its history from the host store, prefills only the tail, and
    generates byte-identically to a cold full prefill."""
    eng = _store_engine()
    ref = InferenceEngine(SPEC, decode_chunk=4, prefill_chunk=CHUNK,
                          n_slots=1)
    conv = _prompt(24)
    gen1 = eng.generate(conv, max_new_tokens=6, sampler=GREEDY,
                        seed=1).token_ids
    eng.drain_prefix_store()
    # an unrelated request reclaims the ONLY slot: tier-0 reuse is gone
    eng.generate(_prompt(30, base=500), max_new_tokens=4, sampler=GREEDY,
                 seed=9)
    turn2 = conv + gen1 + _prompt(5, base=77)
    got = eng.generate(turn2, max_new_tokens=6, sampler=GREEDY,
                       seed=2).token_ids
    assert eng.prefix_store_hits == 1
    assert eng.prefix_store_tokens_restored >= CHUNK
    m = eng.metrics()
    assert m["prefix_store_hits_total"] == 1
    assert m["prefix_store_restored_tokens_total"] >= CHUNK
    cold = ref.generate(turn2, max_new_tokens=6, sampler=GREEDY,
                        seed=2).token_ids
    assert got == cold, "host-store restore changed the generation"


@slow
def test_churn_restore_matches_cold_sampled():
    """Same churn scenario under real sampling: the restore must reproduce
    the RNG-chained stream exactly, not just the greedy argmax path."""
    sampled = SamplerConfig(temperature=0.9, top_p=0.95)
    eng = _store_engine()
    ref = InferenceEngine(SPEC, decode_chunk=4, prefill_chunk=CHUNK,
                          n_slots=1)
    conv = _prompt(24, base=9)
    gen1 = eng.generate(conv, max_new_tokens=6, sampler=sampled,
                        seed=3).token_ids
    eng.drain_prefix_store()
    eng.generate(_prompt(30, base=600), max_new_tokens=4, sampler=GREEDY)
    turn2 = conv + gen1 + _prompt(5, base=42)
    got = eng.generate(turn2, max_new_tokens=8, sampler=sampled,
                       seed=4).token_ids
    assert eng.prefix_store_hits == 1
    cold = ref.generate(turn2, max_new_tokens=8, sampler=sampled,
                        seed=4).token_ids
    assert got == cold


@slow
def test_restore_transfers_only_tail_past_slot_resident_overlap():
    """When the claimed slot already holds a resident prefix of the prompt
    and the store's match is longer, only the tail past the overlap crosses
    host→device: the overlap stays a tier-0 hit and the restored-token
    accounting reports the store's actual contribution."""
    shared = _prompt(16, base=3)
    conv = shared + _prompt(16, base=101)
    eng = _store_engine()
    ref = InferenceEngine(SPEC, decode_chunk=4, prefill_chunk=CHUNK,
                          n_slots=1)
    gen1 = eng.generate(conv, max_new_tokens=6, sampler=GREEDY,
                        seed=11).token_ids
    eng.drain_prefix_store()
    # a request SHARING the first chunk reclaims the only slot: the slot
    # keeps a 16-token resident overlap with the conversation, while the
    # store still holds its full 32-token prefix
    eng.generate(shared + _prompt(20, base=202), max_new_tokens=4,
                 sampler=GREEDY, seed=12)
    eng.drain_prefix_store()
    saved0 = eng.prefix_tokens_saved
    turn2 = conv + gen1 + _prompt(5, base=77)
    got = eng.generate(turn2, max_new_tokens=6, sampler=GREEDY,
                       seed=13).token_ids
    assert eng.prefix_store_hits == 1
    # 32 matched, 16 already slot-resident: only the 16-token tail restores
    assert eng.prefix_store_tokens_restored == CHUNK
    assert eng.prefix_tokens_saved - saved0 == CHUNK
    cold = ref.generate(turn2, max_new_tokens=6, sampler=GREEDY,
                        seed=13).token_ids
    assert got == cold, "tail-only restore changed the generation"


@slow
def test_store_composes_with_kv_quant_int8():
    """The store holds the cache's NATIVE representation: with
    kv_quant=int8 the restored prefix is the same int8+scale bytes prefill
    wrote (output equality), and host bytes per token shrink vs bf16."""
    held = {}
    for kvq in (None, "int8"):
        eng = _store_engine(kv_quant=kvq)
        ref = InferenceEngine(SPEC, decode_chunk=4, prefill_chunk=CHUNK,
                              n_slots=1, kv_quant=kvq)
        conv = _prompt(24, base=21)
        gen1 = eng.generate(conv, max_new_tokens=6, sampler=GREEDY,
                            seed=5).token_ids
        eng.drain_prefix_store()
        held[kvq] = eng.prefix_store.bytes_held
        eng.generate(_prompt(30, base=700), max_new_tokens=4, sampler=GREEDY)
        turn2 = conv + gen1 + _prompt(5, base=33)
        got = eng.generate(turn2, max_new_tokens=6, sampler=GREEDY,
                           seed=6).token_ids
        assert eng.prefix_store_hits == 1, kvq
        cold = ref.generate(turn2, max_new_tokens=6, sampler=GREEDY,
                            seed=6).token_ids
        assert got == cold, kvq
    assert held["int8"] < held[None], held


@slow
def test_engine_eviction_honors_byte_budget():
    # llama-tiny, one 16-token bf16 chunk is 4096 bytes (see the store's
    # stats) — a 5000-byte budget holds exactly one chunk.
    eng = _store_engine(prefix_store_bytes=5000)
    eng.generate(_prompt(40, base=5), max_new_tokens=4, sampler=GREEDY)
    eng.generate(_prompt(40, base=900), max_new_tokens=4, sampler=GREEDY)
    eng.drain_prefix_store()
    s = eng.prefix_store.stats()
    assert s["bytes_held"] <= 5000
    assert s["evictions_total"] >= 1
    assert eng.metrics()["prefix_store_evictions_total"] >= 1


@slow
def test_snapshot_is_incremental_across_turns():
    """Turn N+1's release must snapshot only the chunks turn N+1 added —
    the already-covered chain is not re-fetched or re-stored."""
    eng = _store_engine()
    conv = _prompt(24, base=8)
    gen1 = eng.generate(conv, max_new_tokens=6, sampler=GREEDY,
                        seed=7).token_ids
    eng.drain_prefix_store()
    inserts1 = eng.prefix_store.n_inserts
    turn2 = conv + gen1 + _prompt(20, base=90)
    eng.generate(turn2, max_new_tokens=6, sampler=GREEDY, seed=8)
    eng.drain_prefix_store()
    s = eng.prefix_store.stats()
    # turn 2 extended the chain (new entries) without re-inserting turn 1's
    assert s["inserts_total"] > inserts1
    assert s["inserts_total"] == s["entries"]


@slow
def test_members_with_prefix_store_is_config_error():
    with pytest.raises(ValueError, match="prefix_store"):
        InferenceEngine(SPEC, prefill_chunk=CHUNK, members=2,
                        prefix_store="host")
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    with pytest.raises(ValueError, match="prefix_store"):
        TpuBackend.from_spec(BackendSpec(
            name="X",
            url="tpu://llama-tiny?members=2&member=0&prefix_store=host",
            model="m"))


@slow
def test_invalid_store_knobs_rejected():
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    with pytest.raises(ValueError, match="prefix_store"):
        TpuBackend.from_spec(BackendSpec(
            name="X", url="tpu://llama-tiny?prefix_store=disk", model="m"))
    # sizing knobs without the store: a misconfiguration, not a silent no-op
    with pytest.raises(ValueError, match="prefix_store_bytes"):
        TpuBackend.from_spec(BackendSpec(
            name="X", url="tpu://llama-tiny?prefix_store_bytes=1g",
            model="m"))
    with pytest.raises(ValueError, match="prefix_store_bytes"):
        TpuBackend.from_spec(BackendSpec(
            name="X",
            url="tpu://llama-tiny?prefix_store=host&prefix_store_bytes=lots",
            model="m"))
    with pytest.raises(ValueError, match="ensemble"):
        InferenceEngine(SPEC, prefill_chunk=CHUNK, ensemble=2,
                        prefix_store="host")


@slow
def test_store_knob_parses_through_backend_url():
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    b = TpuBackend.from_spec(BackendSpec(
        name="S",
        url=("tpu://llama-tiny?max_seq=64&seed=31&prefix_store=host"
             "&prefix_store_bytes=2m&prefix_store_chunk=16"),
        model="m"))
    assert b.engine.prefix_store is not None
    assert b.engine.prefix_store.max_bytes == 2 << 20
    assert b.engine.prefix_store.chunk_tokens == 16
    m = b.engine.metrics()
    assert m["prefix_store_bytes"] == 0 and m["prefix_store_entries"] == 0
