"""qlint: the hot-path static-analysis suite (fast tier).

Per-rule positive/negative fixtures: a seeded violation (a ``.item()`` in a
hot-path snippet, a guarded-field write outside ``_cond``, a jit-per-call
recompile hazard) must FAIL, the clean twin must PASS — so the checker
itself can never silently rot. Plus the merged-tree gates: the package lints
clean, the baseline stays empty (burn-down only), ``_GUARDED_BY`` covers
every field the engine documents as ``_cond``-guarded, the program-key
budget classifies every live cache key, and the runtime sentinels hold —
a warmed engine compiles nothing, and the decode loop is token-for-token
identical under ``jax.transfer_guard("disallow")``.
"""

import textwrap
import time

import pytest

from quorum_tpu.analysis import budget, compile_watch
from quorum_tpu.analysis import qlint as ql


def _lint(tmp_path, source: str):
    p = tmp_path / "fixture.py"
    p.write_text(textwrap.dedent(source))
    findings, suppressed, _, _ = ql.run_qlint([p])
    return findings


def _kinds(findings):
    return {f.kind for f in findings}


# ---- sync-taboo rule -------------------------------------------------------


def test_sync_item_call_flagged(tmp_path):
    fs = _lint(tmp_path, """
        def hot(x):
            return x.item()
    """)
    assert "item-call" in _kinds(fs)


def test_sync_tolist_and_np_asarray_flagged(tmp_path):
    fs = _lint(tmp_path, """
        import numpy as np
        def hot(x):
            a = x.tolist()
            b = np.asarray(x)
            return a, b
    """)
    assert {"tolist-call", "np-asarray"} <= _kinds(fs)


def test_sync_device_tracked_cast_and_truthiness_flagged(tmp_path):
    fs = _lint(tmp_path, """
        import jax.numpy as jnp
        def hot(x):
            y = jnp.sum(x)
            if y:                 # truthiness on a device array
                pass
            return float(y)       # blocking scalar cast
    """)
    assert {"array-truthiness", "host-scalar-cast"} <= _kinds(fs)


def test_sync_clean_host_path_passes(tmp_path):
    fs = _lint(tmp_path, """
        import numpy as np
        def _host_fetch(*xs):
            ...
        def hot(payload):
            fetched = _host_fetch(payload)
            toks = np.asarray(fetched)       # already on host
            vals = [float(v) for v in toks]  # host floats
            return toks.tolist(), vals       # host tolist
    """)
    assert fs == []


def test_sync_block_until_ready_needs_annotation(tmp_path):
    fs = _lint(tmp_path, """
        import jax
        def hot(x):
            jax.block_until_ready(x)
    """)
    assert "block-until-ready" in _kinds(fs)


def test_sync_annotated_suppression_with_reason_passes(tmp_path):
    fs = _lint(tmp_path, """
        import jax
        def hot(x):
            # qlint: allow-sync(bench-only drain point)
            jax.block_until_ready(x)
    """)
    assert fs == []


def test_sync_empty_suppression_reason_fails(tmp_path):
    fs = _lint(tmp_path, """
        import jax
        def hot(x):
            jax.block_until_ready(x)  # qlint: allow-sync()
    """)
    assert "empty-suppression-reason" in _kinds(fs)


# ---- recompile-budget rule -------------------------------------------------


def test_recompile_jit_immediate_call_flagged(tmp_path):
    fs = _lint(tmp_path, """
        import jax
        def rebuild(f, x):
            return jax.jit(f)(x)
    """)
    assert "jit-immediate-call" in _kinds(fs)


def test_recompile_jit_in_loop_flagged(tmp_path):
    fs = _lint(tmp_path, """
        import jax
        def build(fns):
            out = []
            for f in fns:
                out.append(jax.jit(f))
            return out
    """)
    assert "jit-in-loop" in _kinds(fs)


def test_recompile_non_pow2_shape_knob_flagged(tmp_path):
    fs = _lint(tmp_path, """
        def make(engine_cls):
            return engine_cls(decode_chunk=6)
    """)
    assert "non-pow2-shape-knob" in _kinds(fs)


def test_recompile_cached_wrapper_passes(tmp_path):
    fs = _lint(tmp_path, """
        import jax
        _CACHE = {}
        def get_fn(key, f):
            fn = _CACHE.get(key)
            if fn is None:
                fn = _CACHE[key] = jax.jit(f)
            return fn
        def make(engine_cls):
            return engine_cls(decode_chunk=8)
    """)
    assert fs == []


# ---- guarded-by rule -------------------------------------------------------

_GUARDED_HEADER = """
    import threading
    _GUARDED_BY = {
        "_pending": {"lock": "_cond"},
        "_slots": {"lock": "_cond", "holders": ["_release_slot"]},
        "_inflight": {"owner": ["_fill", "_drain"]},
    }
    class Engine:
        def __init__(self):
            self._cond = threading.Condition()
            self._pending = []   # __init__ precedes publication: exempt
            self._slots = [None]
            self._inflight = []
"""


def test_guarded_unlocked_mutation_flagged(tmp_path):
    fs = _lint(tmp_path, _GUARDED_HEADER + """
        def submit(self, req):
            self._pending.append(req)    # no lock: the PR 3/4/7 race class
    """)
    assert any(k.startswith("unguarded-append-_pending") for k in _kinds(fs))


def test_guarded_locked_mutation_passes(tmp_path):
    fs = _lint(tmp_path, _GUARDED_HEADER + """
        def submit(self, req):
            with self._cond:
                self._pending.append(req)
                self._slots[0] = req
    """)
    assert fs == []


def test_guarded_subscript_write_outside_lock_flagged(tmp_path):
    fs = _lint(tmp_path, _GUARDED_HEADER + """
        def steal(self, req):
            self._slots[0] = req
    """)
    assert any("unguarded-write-_slots" in k for k in _kinds(fs))


def test_guarded_holder_method_passes(tmp_path):
    fs = _lint(tmp_path, _GUARDED_HEADER + """
        def _release_slot(self, i):
            self._slots[i] = None        # documented: caller holds _cond
    """)
    assert fs == []


def test_guarded_single_owner_methods(tmp_path):
    fs = _lint(tmp_path, _GUARDED_HEADER + """
        def _fill(self, c):
            self._inflight.append(c)     # owner thread: fine, no lock
        def elsewhere(self, c):
            self._inflight.append(c)     # not an owner: race
    """)
    kinds = _kinds(fs)
    assert any("unguarded-append-_inflight" in k for k in kinds)
    assert len([f for f in fs if "_inflight" in f.kind]) == 1


def test_guarded_allow_unguarded_annotation(tmp_path):
    fs = _lint(tmp_path, _GUARDED_HEADER + """
        def racy_but_ok(self, req):
            # qlint: allow-unguarded(write happens before thread start)
            self._pending.append(req)
    """)
    assert fs == []


# ---- merged-tree gates -----------------------------------------------------


def test_package_lints_clean_and_fast():
    t0 = time.perf_counter()
    new, suppressed, stale, _ = ql.run_qlint()
    dt = time.perf_counter() - t0
    assert new == [], [f.render() for f in new]
    assert dt < 10.0, f"qlint took {dt:.1f}s; budget is 10s"
    # every suppression in the tree carries a reason (enforced by the
    # checker; this pins that the count stays deliberate)
    assert all(reason for _, reason in suppressed)


def test_baseline_is_empty_and_shrink_only():
    base = ql.load_baseline()
    assert base["findings"] == [], (
        "the shipped baseline must stay empty: fix or reason-annotate "
        "findings instead of baselining them")
    assert base["max_count"] == 0


def test_baseline_update_refuses_to_grow(tmp_path):
    import json

    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({"max_count": 0, "findings": []}))
    finding = ql.Finding("sync", "item-call", "x.py", 1, "hot", "msg")
    with pytest.raises(SystemExit, match="refusing to grow"):
        ql.update_baseline([finding], path=base)
    # shrink (or stay) is always allowed
    data = ql.update_baseline([], path=base)
    assert data["findings"] == [] and data["max_count"] == 0


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def hot(x):\n    return x.item()\n")
    assert ql.main([str(bad)]) == 1
    good = tmp_path / "good.py"
    good.write_text("def cold(x):\n    return x\n")
    assert ql.main([str(good)]) == 0
    assert ql.main([]) == 0  # the merged tree is clean


def test_guarded_map_covers_documented_scheduler_state():
    from quorum_tpu.engine import engine as eng_mod

    gm = eng_mod._GUARDED_BY
    # the fields the "Scheduler state, guarded by _cond's lock" block
    # promises — the map is the machine-checked source of truth for them
    for field in ("_pending", "_slots", "_admitting", "_claimed"):
        assert gm[field].get("lock") == "_cond", field
    # the cross-loop queues added by PR 3/7 ride the same lock
    for field in ("_handoffs", "_pending_snaps", "_pending_dfa_resets"):
        assert gm[field].get("lock") == "_cond", field


# ---- program-key budget ----------------------------------------------------


def test_budget_classifies_every_documented_family():
    assert budget.classify_decode_key((4, False, 32)) == "plain"
    assert budget.classify_decode_key(("verify", 4, False, 64)) == "verify"
    assert budget.classify_decode_key(
        ("dfa_verify", 4, False, 64, 8)) == "dfa_verify"
    assert budget.classify_decode_key(
        ("spec_loop", 2, 4, False, 64)) == "spec_loop"
    assert budget.classify_decode_key(
        ("spec_loop_dfa", 2, 4, False, 64, 8)) == "spec_loop_dfa"
    assert budget.classify_decode_key(("dfa", 4, False, 32, 8)) == "dfa"
    assert budget.classify_decode_key(("loop", 4, 4, False, 64)) == "loop"
    assert budget.classify_decode_key(
        ("loop", 4, "dfa", 4, False, 64, 8)) == "loop_dfa"
    assert budget.classify_admit_key(16) == "single_shot"
    assert budget.classify_admit_key("register") == "register"
    assert budget.classify_admit_key(("seg", 16, 64)) == "seg"
    assert budget.classify_admit_key(("hslice", 32)) == "hslice"


def test_budget_rejects_unknown_and_drifted_keys():
    with pytest.raises(budget.UnbudgetedProgramKey):
        budget.classify_decode_key(("mystery", 1, 2))
    with pytest.raises(budget.UnbudgetedProgramKey):
        # a 4th component on the plain key = program-key drift
        budget.classify_decode_key((4, False, 32, 99))
    with pytest.raises(budget.UnbudgetedProgramKey):
        budget.classify_admit_key(("seg", 16))  # dropped history component


# ---- runtime sentinels -----------------------------------------------------


def _tiny_engine(**kw):
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import MODEL_PRESETS

    return InferenceEngine(MODEL_PRESETS["llama-tiny"], decode_chunk=4,
                           **kw)


def test_decode_loop_is_clean_under_transfer_guard_disallow():
    """The acceptance pin: decode-path output under jax.transfer_guard
    ("disallow") — dispatch ring, reap, pipelining — is token-for-token
    the unguarded output, i.e. the token critical path performs zero
    implicit transfers. (conftest defaults the whole suite to the guard;
    this test pins both modes explicitly so the contract survives a
    conftest change.)"""
    from quorum_tpu.ops.sampling import SamplerConfig

    greedy = SamplerConfig(temperature=0.0)
    e_off = _tiny_engine(decode_pipeline=2, transfer_guard="")
    try:
        want = e_off.generate([5, 6, 7], max_new_tokens=16,
                              sampler=greedy).token_ids
    finally:
        e_off.shutdown()
    e_on = _tiny_engine(decode_pipeline=2, transfer_guard="disallow")
    try:
        got = e_on.generate([5, 6, 7], max_new_tokens=16,
                            sampler=greedy).token_ids
    finally:
        e_on.shutdown()
    assert got == want and len(got) == 16


def test_transfer_guard_knob_validated():
    with pytest.raises(ValueError):
        _tiny_engine(transfer_guard="definitely-not-a-level")


def test_transfer_guard_env_typo_is_loud_off_not_a_crash(monkeypatch):
    """The env-knob convention (QUORUM_TPU_FLASH_DECODE precedent): a typo
    in the serving environment must not take engine construction down —
    it logs loudly and runs with the guard OFF."""
    monkeypatch.setenv("QUORUM_TPU_TRANSFER_GUARD", "Disallow")  # bad case
    eng = _tiny_engine()
    try:
        assert eng.transfer_guard is None
    finally:
        eng.shutdown()


def test_warmed_engine_compiles_nothing():
    """The log-compiles hook behind compile_budget.json: a second,
    identical generation on a warmed engine must trigger ZERO new XLA
    compiles — any new program family fails here loudly, whatever its
    cache key looks like."""
    from quorum_tpu.ops.sampling import SamplerConfig

    greedy = SamplerConfig(temperature=0.0)
    eng = _tiny_engine(decode_pipeline=2)
    try:
        first = eng.generate([5, 6, 7], max_new_tokens=12,
                             sampler=greedy).token_ids
        before = compile_watch.compiles_total()
        second = eng.generate([5, 6, 7], max_new_tokens=12,
                              sampler=greedy).token_ids
        grew = compile_watch.compiles_total() - before
        assert grew == 0, (
            f"{grew} XLA compile(s) on a warmed engine: a program family "
            "leaked past compile_budget.json")
        assert first == second
    finally:
        eng.shutdown()


def test_recompiles_total_counts_post_warmup_compiles():
    import jax
    import jax.numpy as jnp

    from quorum_tpu import observability as obs

    compile_watch.install()
    was_warm = compile_watch.is_warm()
    try:
        compile_watch.mark_warm()
        before = obs.RECOMPILES.value
        # a program jax has never seen: its compile must land on the counter
        jax.jit(lambda x: x * 3 + 0.123456)(jnp.ones((3,)))
        assert obs.RECOMPILES.value > before
    finally:
        if not was_warm:
            compile_watch.reset_for_tests()
