"""Weight-only int8 quantization (``quant=int8``, models/quant.py).

Decode is HBM-bandwidth-bound, so int8 weights halve bytes/token (PERF.md).
These tests pin the accuracy contract (per-channel quantization error bound,
near-lossless logits), the pytree/sharding integration (q8/qs leaves inherit
the parent spec on a real mesh), and end-to-end serving through the engine
and the ``tpu://…&quant=int8`` URL knob.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quorum_tpu.models import init_params, resolve_spec
from quorum_tpu.models.quant import (
    dq,
    is_quantized,
    quantize_leaf,
    quantize_params,
    quantized_param_bytes,
)
from quorum_tpu.models.transformer import forward_logits
from quorum_tpu.parallel import MeshConfig, make_mesh
from quorum_tpu.parallel.sharding import param_shardings

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def test_quantize_leaf_error_bound():
    """|w - dq(q(w))| ≤ scale/2 + bf16 rounding, per channel."""
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 48), jnp.float32)
    q = quantize_leaf(w, axis=-2)
    assert q["q8"].dtype == jnp.int8
    assert q["qs"].shape == (1, 48)
    back = np.asarray(dq(q, jnp.float32), np.float32)
    scale = np.asarray(q["qs"], np.float32)
    err = np.abs(back - np.asarray(w))
    # round-to-nearest: ≤ scale/2 everywhere (dequant here is f32 — exact)
    assert (err <= scale / 2 + 1e-6).all()


def test_dq_passthrough_for_plain_leaves():
    w = jnp.ones((4, 4), jnp.bfloat16)
    assert dq(w) is w
    assert not is_quantized(w)


def test_quantized_logits_near_lossless():
    """Tiny llama: quantized forward tracks bf16 forward closely and agrees
    on the argmax for most positions (weight-only int8 contract)."""
    spec = resolve_spec("llama-tiny")
    params = init_params(spec, seed=0)
    qparams = quantize_params(params)
    assert is_quantized(qparams["blocks"]["wq"])
    assert is_quantized(qparams["tok_emb"])
    assert not is_quantized(qparams["blocks"]["attn_norm_w"])
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, spec.vocab_size)
    ref = np.asarray(forward_logits(params, spec, tokens), np.float32)
    got = np.asarray(forward_logits(qparams, spec, tokens), np.float32)
    # relative L2 error small; argmax agrees on ≥ 90% of positions
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 0.05, f"relative logits error {rel:.4f}"
    agree = (got.argmax(-1) == ref.argmax(-1)).mean()
    assert agree >= 0.9, f"argmax agreement {agree:.2f}"


def test_quantized_moe_forward_runs():
    spec = resolve_spec("mixtral-tiny")
    qparams = quantize_params(init_params(spec, seed=0))
    assert is_quantized(qparams["blocks"]["moe_w_gate"])
    assert not is_quantized(qparams["blocks"]["router"])
    tokens = jnp.ones((1, 8), jnp.int32)
    out = forward_logits(qparams, spec, tokens)
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_quantized_bytes_halved():
    spec = resolve_spec("llama-tiny")
    params = init_params(spec, seed=0)
    bf16_bytes = quantized_param_bytes(params)
    q_bytes = quantized_param_bytes(quantize_params(params))
    # int8 + scales + unquantized norms: well under 60% of bf16
    assert q_bytes < 0.6 * bf16_bytes


def test_quantized_shardings_inherit_parent_spec():
    """q8 gets the parent leaf's PartitionSpec (tp on heads/ff/vocab); the
    size-1 scale dims replicate via _fit_spec."""
    spec = resolve_spec("llama-tiny")
    mesh = make_mesh(MeshConfig(dp=2, tp=2), jax.devices()[:4])
    qtree = jax.eval_shape(lambda: quantize_params(init_params(spec, 0)))
    sh = param_shardings(mesh, qtree)
    wq = sh["blocks"]["wq"]
    assert wq["q8"].spec == jax.sharding.PartitionSpec(None, None, "tp")
    assert wq["qs"].spec == jax.sharding.PartitionSpec(None, None, "tp")


def test_engine_int8_serves_on_mesh():
    """End-to-end: int8 engine on a dp2×tp2 mesh generates deterministically
    and matches its own single-device int8 output token-for-token."""
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = resolve_spec("llama-tiny", {"max_seq": "64"})
    mesh = make_mesh(MeshConfig(dp=2, tp=2), jax.devices()[:4])
    eng_mesh = InferenceEngine(spec, mesh, decode_chunk=4, quant="int8")
    eng_one = InferenceEngine(spec, decode_chunk=4, quant="int8")
    prompt = [3, 5, 7]
    sampler = SamplerConfig(temperature=0.0)
    a = eng_mesh.generate(prompt, max_new_tokens=8, sampler=sampler).token_ids
    b = eng_one.generate(prompt, max_new_tokens=8, sampler=sampler).token_ids
    assert len(a) == 8
    assert a == b, "int8 generation diverged between mesh and single device"


async def test_tpu_url_quant_knob():
    """tpu://…&quant=int8 serves a completion; quant=int4 is rejected."""
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    be = TpuBackend.from_spec(BackendSpec(
        name="Q8", url="tpu://llama-tiny?quant=int8&max_seq=64", model="m",
    ))
    assert be.engine.quant == "int8"
    out = await be.complete(
        {"model": "m", "messages": [{"role": "user", "content": "hi"}],
         "max_tokens": 4},
        {}, timeout=60,
    )
    assert out.status_code == 200
    assert out.body["choices"][0]["message"]["content"] is not None

    with pytest.raises(ValueError):
        TpuBackend.from_spec(BackendSpec(
            name="Q4", url="tpu://llama-tiny?quant=int4", model="m",
        ))


def test_ckpt_quant_logits_close_to_transformers(tmp_path):
    """Real-weights path: a HF checkpoint loaded with quant=int8 still tracks
    the transformers forward (weight mapping + quantization compose)."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from quorum_tpu.models.hf_loader import load_hf_checkpoint

    cfg = LlamaConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    # Deterministic weights: the int8 error/argmax bounds below are tight
    # enough that an unlucky UNSEEDED draw can cross them (observed once in
    # a full-suite run) — that flake tells us nothing about the quantizer.
    torch.manual_seed(0)
    model = LlamaForCausalLM(cfg).eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    tokens = np.array([[3, 17, 5, 9, 250, 11, 42, 7]], dtype=np.int32)
    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.float().numpy()

    spec, params = load_hf_checkpoint(str(tmp_path), dtype="float32")
    qlogits = np.asarray(
        forward_logits(quantize_params(params), spec, jnp.asarray(tokens)),
        np.float32,
    )
    rel = np.linalg.norm(qlogits - theirs) / np.linalg.norm(theirs)
    assert rel < 0.05, f"relative error vs transformers {rel:.4f}"
    agree = (qlogits.argmax(-1) == theirs.argmax(-1)).mean()
    assert agree >= 0.85, f"argmax agreement {agree:.2f}"


def test_native_int8_and_f32_gemm_branches_agree(monkeypatch):
    """The shipping TPU branch (native int8 einsum) must compute the same
    products as the CPU f32-GEMM formulation. At tiny contraction dims the
    f32 accumulation is exact (sums < 2^24), so equality is EXACT — a
    regression in the chip-only branch fails here on CPU."""
    from quorum_tpu.models.quant import qeinsum, quantize_leaf

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(5, 64)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(64, 48)).astype(np.float32))
    leaf = quantize_leaf(w, -2)

    monkeypatch.setenv("QUORUM_TPU_QEINSUM_INT8", "1")  # force native path
    native = np.asarray(qeinsum("td,df->tf", x, leaf))
    monkeypatch.setenv("QUORUM_TPU_QEINSUM_INT8", "0")  # force f32 GEMM
    gemm = np.asarray(qeinsum("td,df->tf", x, leaf))
    np.testing.assert_array_equal(native, gemm)
