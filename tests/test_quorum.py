"""Native quorum serving (quorum_tpu/quorum/, docs/quorum.md).

Fast tier: fanout-knob units, leg failover/4xx-relay units over stub
replicas, and the router-tier quorum end-to-end over jax-free fake
replicas — full fan-out, member-kill degradation (with and without a
spare), token-exact member resume, the streaming chunk contract, and the
single-cell server's explicit quorum rejection. Slow tier: shared-prefix
member dedup on REAL stacked engines — outputs pinned token-for-token
against the M-prefill path (dense + paged, greedy + sampled) with the
(M-1)·n_prompt savings counted, plus the config-time composition
rejections and the engine-cache key split.
"""

import time
from types import SimpleNamespace

import httpx
import pytest

from quorum_tpu import oai
from quorum_tpu.backends.base import BackendError
from quorum_tpu.observability import (
    QUORUM_DEDUP_TOKENS,
    QUORUM_DEGRADED,
    QUORUM_REQUESTS,
)
from quorum_tpu.quorum import fanout
from tests.test_router import _Cluster, _collect, _conv

slow = pytest.mark.slow

SEP = "\n\n---\n\n"  # RouterConfig.quorum_separator default
AUTH = {"Authorization": "Bearer sk-test"}


# ---- knob validation units --------------------------------------------------


def test_validate_quorum_shapes():
    ok = [{}, {"quorum": None}, {"quorum": 1}, {"quorum": 3},
          {"quorum": fanout.MAX_QUORUM}, {"quorum": 3, "n": 1}]
    for body in ok:
        assert fanout.validate_quorum(body) is None, body
    bad = [{"quorum": 0}, {"quorum": fanout.MAX_QUORUM + 1},
           {"quorum": True}, {"quorum": "3"}, {"quorum": 2.5},
           {"quorum": 2, "n": 2}, {"quorum": 2, "logprobs": 3},
           {"quorum": 2, "resume_tokens": [1]},
           {"quorum": 2, "stream_token_ids": True}]
    for body in bad:
        assert fanout.validate_quorum(body) is not None, body
    # the shared request validator carries the same checks (server + router)
    assert oai.validate_request_body({"quorum": 3}) is None
    assert oai.validate_request_body({"quorum": 99}) is not None
    assert oai.validate_request_body({"quorum": 3, "n": 2}) is not None


def test_pop_quorum_strips_the_knob():
    body = {"quorum": 3, "messages": []}
    assert fanout.pop_quorum(body) == 3
    assert "quorum" not in body  # never forwarded: would recurse at replicas
    assert fanout.pop_quorum({}) == 1
    assert fanout.pop_quorum({"quorum": None}) == 1
    assert fanout.pop_quorum({"quorum": True}) == 1


def test_choose_members_splits_ring_order():
    assert fanout.choose_members(["a", "b", "c", "d"], 2) == \
        (["a", "b"], ["c", "d"])
    assert fanout.choose_members(["a", "b"], 3) == (["a", "b"], [])


def test_summarize_and_headers():
    legs = [fanout.QuorumLeg(index=i) for i in range(3)]
    assert fanout.summarize(3, legs) == ("failed", [])
    legs[0].ok = True
    legs[0].content = "x"
    legs[0].replica = "r0"
    legs[1].ok = True
    legs[1].content = "y"
    legs[1].replica = "r2"
    legs[2].degraded_reason = "stream_broken"
    outcome, served = fanout.summarize(3, legs)
    assert outcome == "degraded" and len(served) == 2
    h = fanout.quorum_headers(3, legs, outcome)
    assert h["X-Quorum-Members"] == "3"
    assert h["X-Quorum-Served"] == "2"
    assert h["X-Quorum-Replicas"] == "r0,r2"
    assert h["X-Quorum-Degraded"] == "stream_broken"
    legs[2].ok = True
    legs[2].content = "z"
    legs[2].degraded_reason = None
    outcome, _ = fanout.summarize(3, legs)
    assert outcome == "full"
    assert "X-Quorum-Degraded" not in fanout.quorum_headers(3, legs, outcome)


# ---- leg units over stub replicas -------------------------------------------


class _StubBreaker:
    def allow(self):
        return True

    def record_success(self):
        pass

    def record_failure(self):
        pass


def _stub_replica(name, complete):
    async def _complete(body, headers, timeout):
        return complete()

    return SimpleNamespace(
        name=name, inflight=0, requests=0, breaker=_StubBreaker(),
        backend=SimpleNamespace(complete=_complete))


def _ok_result(text):
    return SimpleNamespace(
        status_code=200,
        body={"id": "chatcmpl-1", "object": "chat.completion",
              "created": 1, "model": "m",
              "choices": [{"index": 0, "message": {
                  "role": "assistant", "content": text},
                  "finish_reason": "stop"}]},
        usage={"prompt_tokens": 2, "completion_tokens": 3,
               "total_tokens": 5})


async def test_leg_retries_5xx_on_spare_then_serves():
    def die():
        raise BackendError("boom", status_code=503)

    replicas = {"a": _stub_replica("a", die),
                "b": _stub_replica("b", lambda: _ok_result("B"))}
    body, status, hdrs = await fanout.quorum_complete(
        replicas, ["a", "b"], 1, {"messages": []}, {},
        time.monotonic() + 5, "rid-1", SEP)
    assert status == 200
    assert body["choices"][0]["message"]["content"] == "B"
    assert body["quorum"] == {"members": 1, "served": 1,
                              "replicas": ["b"], "degraded": []}
    assert hdrs["X-Quorum-Replicas"] == "b"


async def test_all_4xx_quorum_relays_the_client_error():
    """An all-4xx quorum is the CLIENT's error: the real upstream body and
    status come back, not a 502 proxy_error wrapper."""
    err = {"error": {"message": "bad knob", "type": "invalid_request_error"}}

    def reject():
        raise BackendError("bad knob", status_code=422, body=err)

    replicas = {n: _stub_replica(n, reject) for n in ("a", "b")}
    before = QUORUM_REQUESTS.value_of(outcome="failed")
    body, status, _ = await fanout.quorum_complete(
        replicas, ["a", "b"], 2, {"messages": []}, {},
        time.monotonic() + 5, "rid-2", SEP)
    assert (status, body) == (422, err)
    assert QUORUM_REQUESTS.value_of(outcome="failed") == before + 1


async def test_empty_member_drops_as_no_content():
    replicas = {"a": _stub_replica("a", lambda: _ok_result("")),
                "b": _stub_replica("b", lambda: _ok_result("B"))}
    before = QUORUM_DEGRADED.value_of(reason="no_content")
    body, status, hdrs = await fanout.quorum_complete(
        replicas, ["a", "b"], 2, {"messages": []}, {},
        time.monotonic() + 5, "rid-3", SEP)
    assert status == 200
    assert body["choices"][0]["message"]["content"] == "B"
    assert body["quorum"]["degraded"] == [
        {"member": 0, "reason": "no_content"}]
    assert hdrs["X-Quorum-Degraded"] == "no_content"
    assert QUORUM_DEGRADED.value_of(reason="no_content") == before + 1


# ---- router e2e over fake replicas ------------------------------------------


async def test_quorum_complete_full_over_three_replicas():
    async with _Cluster(3) as c:
        single = await c.chat(_conv(0))
        assert single.status_code == 200
        t = single.json()["choices"][0]["message"]["content"]
        u = single.json()["usage"]

        before = QUORUM_REQUESTS.value_of(outcome="full")
        r = await c.chat(_conv(0), quorum=3)
        assert r.status_code == 200, r.text
        assert r.headers["x-quorum-members"] == "3"
        assert r.headers["x-quorum-served"] == "3"
        assert "x-quorum-degraded" not in r.headers
        served = r.headers["x-quorum-replicas"].split(",")
        assert sorted(served) == ["r0", "r1", "r2"]  # distinct cells
        data = r.json()
        # every member runs the same scripted prompt → identical answers,
        # combined in member order with the configured separator
        assert data["choices"][0]["message"]["content"] == SEP.join([t] * 3)
        assert data["quorum"]["members"] == 3
        assert data["quorum"]["served"] == 3
        assert data["quorum"]["degraded"] == []
        assert sorted(data["quorum"]["replicas"]) == ["r0", "r1", "r2"]
        assert data["usage"]["completion_tokens"] == \
            3 * u["completion_tokens"]
        assert QUORUM_REQUESTS.value_of(outcome="full") == before + 1
        # the knob never reached a replica (it would recurse the fan-out)
        assert all("quorum" not in call
                   for st in c.states for call in st.seen_bodies)


async def test_quorum_member_kill_with_spare_stays_full():
    async with _Cluster(4) as c:
        base = await c.chat(_conv(1), quorum=3)
        assert base.status_code == 200
        assigned = base.headers["x-quorum-replicas"].split(",")
        spare = ({"r0", "r1", "r2", "r3"} - set(assigned)).pop()
        victim = assigned[0]
        c.states[int(victim[1:])].shedding = True  # every request now 503s

        before = QUORUM_REQUESTS.value_of(outcome="full")
        r = await c.chat(_conv(1), quorum=3)
        assert r.status_code == 200, r.text
        assert r.headers["x-quorum-served"] == "3"  # spare covered the kill
        assert "x-quorum-degraded" not in r.headers
        now_served = r.headers["x-quorum-replicas"].split(",")
        assert victim not in now_served and spare in now_served
        assert r.json()["choices"][0]["message"]["content"] == \
            base.json()["choices"][0]["message"]["content"]
        assert QUORUM_REQUESTS.value_of(outcome="full") == before + 1


async def test_quorum_member_kill_without_spare_degrades():
    async with _Cluster(3) as c:
        single = await c.chat(_conv(2))
        t = single.json()["choices"][0]["message"]["content"]
        c.states[0].shedding = True  # one member down, no spare exists

        d_before = QUORUM_DEGRADED.value_of(reason="member_failed")
        o_before = QUORUM_REQUESTS.value_of(outcome="degraded")
        r = await c.chat(_conv(2), quorum=3)
        assert r.status_code == 200, r.text  # served, never failed
        assert r.headers["x-quorum-served"] == "2"
        assert r.headers["x-quorum-degraded"] == "member_failed"
        data = r.json()
        assert data["choices"][0]["message"]["content"] == SEP.join([t] * 2)
        assert [d["reason"] for d in data["quorum"]["degraded"]] == \
            ["member_failed"]
        assert QUORUM_DEGRADED.value_of(reason="member_failed") \
            == d_before + 1
        assert QUORUM_REQUESTS.value_of(outcome="degraded") == o_before + 1


async def test_quorum_all_members_dead_fails_with_502():
    async with _Cluster(3) as c:
        for srv in c.servers:
            srv.close()
            await srv.wait_closed()
        before = QUORUM_REQUESTS.value_of(outcome="failed")
        r = await c.chat(_conv(3), quorum=3)
        assert r.status_code == 502
        assert "quorum failed" in r.json()["error"]["message"]
        assert r.headers["x-quorum-served"] == "0"
        assert QUORUM_REQUESTS.value_of(outcome="failed") == before + 1


async def test_quorum_router_validation_and_passthrough():
    async with _Cluster(2) as c:
        for bad in ({"quorum": 99}, {"quorum": 3, "n": 2},
                    {"quorum": 3, "stream_token_ids": True}):
            r = await c.chat(_conv(4), **bad)
            assert r.status_code == 400, bad
            assert r.json()["error"]["type"] == "invalid_request_error"
        # quorum=1 is a no-op: the plain single-replica path, knob stripped
        r = await c.chat(_conv(4), quorum=1)
        assert r.status_code == 200
        assert "x-routed-to" in r.headers
        assert "x-quorum-members" not in r.headers
        assert "quorum" not in r.json()


# ---- router e2e: streaming contract -----------------------------------------


def _by_id(events, id_):
    return "".join((ch.get("delta") or {}).get("content") or ""
                   for e in events if e.get("id") == id_
                   for ch in e.get("choices") or [])


def _final_events(events):
    return [e for e in events if e.get("id") == oai.PARALLEL_FINAL_ID]


async def test_quorum_stream_contract_full():
    async with _Cluster(3) as c:
        plain = {"model": "m", "stream": True, "messages": _conv(5)}
        base_events, _ = await _collect(c, plain)
        t = "".join((ch.get("delta") or {}).get("content") or ""
                    for e in base_events for ch in e.get("choices") or [])
        assert t

        before = QUORUM_REQUESTS.value_of(outcome="full")
        events, headers = await _collect(c, {**plain, "quorum": 3})
        assert headers["x-quorum-members"] == "3"
        assert len(headers["x-quorum-replicas"].split(",")) == 3
        # parallel-proxy chunk contract: one role chunk leads, member
        # deltas ride per-member ids, one combined final closes it
        assert events[0]["id"] == oai.PARALLEL_ID
        assert events[0]["choices"][0]["delta"]["role"] == "assistant"
        for i in range(3):
            assert _by_id(events, f"chatcmpl-parallel-{i}") == t
        finals = _final_events(events)
        assert len(finals) == 1 and finals[-1] is events[-1]
        assert finals[0]["choices"][0]["finish_reason"] == "stop"
        assert finals[0]["choices"][0]["delta"]["content"] == \
            SEP.join([t] * 3)
        assert not any(e.get("id") == "error" for e in events)
        # router-internal resume metadata never reaches the client
        assert not any("qt_tokens" in e or "qt_error" in e for e in events)
        assert QUORUM_REQUESTS.value_of(outcome="full") == before + 1


async def test_quorum_stream_suppress_individual_responses():
    async with _Cluster(3) as c:
        events, _ = await _collect(c, {
            "model": "m", "stream": True, "messages": _conv(6),
            "quorum": 3, "suppress_individual_responses": True})
        ids = {e.get("id") for e in events}
        assert ids == {oai.PARALLEL_ID, oai.PARALLEL_FINAL_ID}
        assert _final_events(events)[0]["choices"][0]["delta"]["content"]


async def test_quorum_stream_member_kill_resumes_token_exact():
    """A member killed mid-stream finishes token-exact on the spare cell:
    the combined answer is identical to the unbroken run and the quorum
    stays full — no degradation counted."""
    async with _Cluster(4) as c:
        body = {"model": "m", "stream": True, "messages": _conv(7),
                "quorum": 3}
        base_events, base_h = await _collect(c, body)
        base_final = _final_events(base_events)[0]
        assigned = base_h["x-quorum-replicas"].split(",")
        spare = ({"r0", "r1", "r2", "r3"} - set(assigned)).pop()
        victim = assigned[0]
        c.states[int(victim[1:])].abort_after = 2

        d_before = QUORUM_DEGRADED.value
        o_before = QUORUM_REQUESTS.value_of(outcome="full")
        spare_reqs = c.states[int(spare[1:])].requests
        events, _ = await _collect(c, body)
        assert not any(e.get("id") == "error" for e in events)
        assert _final_events(events)[0]["choices"][0]["delta"]["content"] \
            == base_final["choices"][0]["delta"]["content"]
        assert c.states[int(spare[1:])].requests > spare_reqs  # resume ran
        assert QUORUM_DEGRADED.value == d_before
        assert QUORUM_REQUESTS.value_of(outcome="full") == o_before + 1


async def test_quorum_stream_member_kill_without_spare_degrades():
    """With no spare left the killed member is dropped — but its already-
    delivered partial answer joins the combine, and the request never sees
    an error chunk."""
    async with _Cluster(3) as c:
        body = {"model": "m", "stream": True, "messages": _conv(8),
                "quorum": 3}
        base_events, base_h = await _collect(c, body)
        t = _by_id(base_events, "chatcmpl-parallel-0")
        victim = base_h["x-quorum-replicas"].split(",")[0]
        c.states[int(victim[1:])].abort_after = 2

        d_before = QUORUM_DEGRADED.value_of(reason="stream_broken")
        o_before = QUORUM_REQUESTS.value_of(outcome="degraded")
        events, _ = await _collect(c, body)
        assert not any(e.get("id") == "error" for e in events)
        pieces = _final_events(events)[0]["choices"][0]["delta"][
            "content"].split(SEP)
        assert len(pieces) == 3  # the partial still contributes
        assert pieces.count(t) == 2
        partial = next(p for p in pieces if p != t)
        assert partial and t.startswith(partial)
        assert QUORUM_DEGRADED.value_of(reason="stream_broken") \
            == d_before + 1
        assert QUORUM_REQUESTS.value_of(outcome="degraded") == o_before + 1


async def test_quorum_stream_all_dead_degrades_to_error_chunk():
    async with _Cluster(3) as c:
        for srv in c.servers:
            srv.close()
            await srv.wait_closed()
        before = QUORUM_REQUESTS.value_of(outcome="failed")
        events, _ = await _collect(c, {
            "model": "m", "stream": True, "messages": _conv(9), "quorum": 3})
        assert events[0]["id"] == oai.PARALLEL_ID
        errors = [e for e in events if e.get("id") == "error"]
        assert len(errors) == 1
        assert "quorum failed" in errors[0]["choices"][0]["delta"]["content"]
        assert QUORUM_REQUESTS.value_of(outcome="failed") == before + 1


# ---- single-cell server rejects the knob ------------------------------------


async def test_single_cell_server_rejects_quorum():
    from quorum_tpu.backends import FakeBackend
    from tests.conftest import make_client

    cfg = {"settings": {"timeout": 7},
           "primary_backends": [{"name": "LLM1", "url": "http://x/v1",
                                 "model": "m"}]}
    fake = FakeBackend("LLM1", text="ok")
    async with make_client(cfg, LLM1=fake) as client:
        r = await client.post(
            "/chat/completions",
            json={"model": "m", "quorum": 2,
                  "messages": [{"role": "user", "content": "q"}]},
            headers=AUTH)
        assert r.status_code == 400
        assert "router tier" in r.json()["error"]["message"]
        assert fake.calls == []  # rejected before any backend dispatch
        # quorum=1 is the no-op spelling everywhere
        r = await client.post(
            "/chat/completions",
            json={"model": "m", "quorum": 1,
                  "messages": [{"role": "user", "content": "q"}]},
            headers=AUTH)
        assert r.status_code == 200
        assert "quorum" not in fake.calls[0].body


# ---- shared-prefix member dedup (slow: engine-scale) ------------------------


def _fan(eng, prompt, sampler, seed=7, n=8):
    """The quorum fan-out shape: one submit per member, same prompt.
    Per-member seeds (``seed+m``) — on a shared-weights stack one seed
    would collapse every sampled stream into member 0's."""
    reqs = [eng.submit(list(prompt), max_new_tokens=n, sampler=sampler,
                       seed=seed + m, member=m)
            for m in range(eng.members)]
    return [list(eng.stream_results(r)) for r in reqs]


def _fan_until_dedup(eng, want, prompt, sampler, attempts=10, **kw):
    """Outputs must match ``want`` on EVERY attempt (dedup or fallback —
    the path taken is timing-dependent: a group only dedups when all M
    submits coalesce into one admission); returns once a dedup admission
    was actually counted."""
    for _ in range(attempts):
        before = eng.quorum_dedup_prefills
        assert _fan(eng, prompt, sampler, **kw) == want
        if eng.quorum_dedup_prefills > before:
            return
    raise AssertionError(
        f"no coalesced dedup admission in {attempts} fan-outs")


@slow
def test_dedup_dense_token_identity_and_savings():
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import MODEL_PRESETS
    from quorum_tpu.ops.sampling import SamplerConfig

    tiny = MODEL_PRESETS["llama-tiny"]
    m = 3
    kw = dict(seed=0, members=m, decode_chunk=4, n_slots=2,
              member_seeds="shared", prefix_cache=False)
    ref = InferenceEngine(tiny, **kw)
    dd = InferenceEngine(tiny, quorum_dedup=True, **kw)
    prompt = [3, 4, 5, 6]
    greedy = SamplerConfig(temperature=0.0)
    sampled = SamplerConfig(temperature=0.8, top_p=0.9)
    try:
        obs_before = QUORUM_DEDUP_TOKENS.value
        want_g = _fan(ref, prompt, greedy)
        # shared weights + greedy: every member IS the same stream
        assert len({tuple(w) for w in want_g}) == 1
        want_s = _fan(ref, prompt, sampled)
        # shared weights + per-member PRNG: the samples usually diverge
        assert len({tuple(w) for w in want_s}) > 1

        _fan_until_dedup(dd, want_g, prompt, greedy)
        _fan_until_dedup(dd, want_s, prompt, sampled)
        # the gate: every dedup admission skipped (M-1)·n_prompt tokens
        assert dd.quorum_dedup_prefills >= 2
        assert dd.quorum_dedup_tokens == \
            dd.quorum_dedup_prefills * (m - 1) * len(prompt)
        assert QUORUM_DEDUP_TOKENS.value - obs_before \
            == dd.quorum_dedup_tokens
        assert ref.quorum_dedup_prefills == 0  # knob off → path never taken

        # partial groups fall back: a lone member admission cannot dedup
        # but stays token-for-token
        before = dd.quorum_dedup_prefills
        one = list(dd.stream_results(dd.submit(
            list(prompt), max_new_tokens=8, sampler=greedy, seed=8,
            member=1)))
        assert one == want_g[1]
        assert dd.quorum_dedup_prefills == before
        # per-member prompt edits fall back too
        other = [9, 8, 7]
        want_mixed = [
            list(ref.stream_results(ref.submit(
                list(p), max_new_tokens=8, sampler=sampled, seed=7,
                member=i)))
            for i, p in enumerate([prompt, other, prompt])]
        got_mixed = [
            list(dd.stream_results(dd.submit(
                list(p), max_new_tokens=8, sampler=sampled, seed=7,
                member=i)))
            for i, p in enumerate([prompt, other, prompt])]
        assert got_mixed == want_mixed
    finally:
        ref.shutdown()
        dd.shutdown()


@slow
def test_dedup_paged_token_identity_and_savings():
    """kv_pages=1: the broadcast rides the slot group's ONE shared page
    chain (page aliasing) — same token-for-token pin, same savings."""
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = resolve_spec("llama-tiny", {"max_seq": "128"})
    m = 3
    kw = dict(seed=0, members=m, decode_chunk=4, n_slots=2,
              member_seeds="shared", prefix_cache=False,
              kv_pages=True, kv_page_size=16)
    ref = InferenceEngine(spec, **kw)
    dd = InferenceEngine(spec, quorum_dedup=True, **kw)
    prompt = [(3 + 7 * i) % 500 for i in range(20)]  # spans >1 page
    greedy = SamplerConfig(temperature=0.0)
    sampled = SamplerConfig(temperature=0.8, top_p=0.9)
    try:
        want_g = _fan(ref, prompt, greedy)
        want_s = _fan(ref, prompt, sampled)
        _fan_until_dedup(dd, want_g, prompt, greedy)
        _fan_until_dedup(dd, want_s, prompt, sampled)
        assert dd.quorum_dedup_tokens == \
            dd.quorum_dedup_prefills * (m - 1) * len(prompt)
    finally:
        ref.shutdown()
        dd.shutdown()


def test_quorum_dedup_config_rejections():
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import MODEL_PRESETS

    tiny = MODEL_PRESETS["llama-tiny"]
    with pytest.raises(ValueError, match="unknown member_seeds"):
        InferenceEngine(tiny, members=2, member_seeds="same")
    with pytest.raises(ValueError, match="ensemble"):
        InferenceEngine(tiny, ensemble=2, member_seeds="shared")
    with pytest.raises(ValueError, match="requires members>1"):
        InferenceEngine(tiny, quorum_dedup=True)
    with pytest.raises(ValueError, match="member_seeds=shared"):
        InferenceEngine(tiny, members=2, quorum_dedup=True)
    with pytest.raises(ValueError, match="kv_quant"):
        InferenceEngine(tiny, members=2, member_seeds="shared",
                        quorum_dedup=True, kv_quant="int8")


@slow
def test_dedup_engine_url_and_cache_key():
    """tpu:// knob plumbing: member_seeds=shared&quorum_dedup=1 reach the
    engine, and the shared-engine cache keys distinct/shared/dedup
    variants apart (a shared-weights stack must never be handed to a
    distinct-seeds member fan)."""
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec
    from quorum_tpu.engine.engine import get_engine
    from quorum_tpu.models.model_config import resolve_spec

    b = TpuBackend.from_spec(BackendSpec(
        name="Q0",
        url="tpu://llama-tiny?members=2&member=0&member_seeds=shared"
            "&quorum_dedup=1&slots=1&max_seq=64",
        model="m"))
    assert b.engine.member_seeds == "shared"
    assert b.engine.quorum_dedup is True

    spec = resolve_spec("llama-tiny", {"max_seq": "64"})
    shared = get_engine(spec, seed=401, members=2, n_slots=1,
                        member_seeds="shared")
    distinct = get_engine(spec, seed=401, members=2, n_slots=1)
    dedup = get_engine(spec, seed=401, members=2, n_slots=1,
                       member_seeds="shared", quorum_dedup=True)
    assert len({id(shared), id(distinct), id(dedup)}) == 3
