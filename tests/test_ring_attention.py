"""Ring attention vs full attention on the virtual 8-device CPU mesh.

Sequence sharded over sp; batch over dp; heads over tp — only sp
communicates (ppermute per ring step). Reference: the XLA-native
prefill_attention, itself validated against transformers' forward.
"""

import numpy as np

import jax
import jax.numpy as jnp

from quorum_tpu.ops.attention import prefill_attention
from quorum_tpu.parallel.mesh import MeshConfig, make_mesh
from quorum_tpu.parallel.ring_attention import ring_prefill_attention

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def rand(seed, shape):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)


def run_case(mesh_cfg, b, h, s, hd, lengths):
    mesh = make_mesh(mesh_cfg)
    q = rand(0, (b, h, s, hd))
    k = rand(1, (b, h, s, hd))
    v = rand(2, (b, h, s, hd))
    lengths = jnp.asarray(lengths, jnp.int32)
    out = ring_prefill_attention(q, k, v, lengths, mesh)
    ref = prefill_attention(q, k, v, lengths)
    return np.asarray(out), np.asarray(ref), np.asarray(lengths)


def check_valid(out, ref, lengths, atol=2e-5):
    for bi, n in enumerate(lengths):
        np.testing.assert_allclose(
            out[bi, :, :n, :], ref[bi, :, :n, :], atol=atol, rtol=1e-4
        )


def test_ring_sp4_matches_full():
    out, ref, lengths = run_case(MeshConfig(sp=4), 1, 2, 64, 16, [64])
    check_valid(out, ref, lengths)


def test_ring_sp8_long_sequence():
    out, ref, lengths = run_case(MeshConfig(sp=8), 1, 2, 128, 16, [128])
    check_valid(out, ref, lengths)


def test_ring_composes_with_dp_and_tp():
    """Full dp2 × sp2 × tp2 mesh: batch and heads shard too; only the ring
    communicates across sp."""
    out, ref, lengths = run_case(MeshConfig(dp=2, sp=2, tp=2), 2, 2, 64, 16, [64, 64])
    check_valid(out, ref, lengths)


def test_ring_respects_lengths():
    out, ref, lengths = run_case(MeshConfig(sp=4), 2, 2, 64, 16, [30, 55])
    check_valid(out, ref, lengths)
    assert not np.isnan(out).any()


def test_ring_gqa_grouped_inside_ring():
    """GQA: k/v enter the ring at KV-head width (no repeat_kv broadcast,
    VERDICT r2 weakness 3) and must match the grouped reference attention."""
    mesh = make_mesh(MeshConfig(sp=4))
    b, h, n_kv, s, hd = 2, 8, 2, 64, 16
    q = rand(0, (b, h, s, hd))
    k = rand(1, (b, n_kv, s, hd))
    v = rand(2, (b, n_kv, s, hd))
    lengths = jnp.asarray([64, 40], jnp.int32)
    out = np.asarray(ring_prefill_attention(q, k, v, lengths, mesh))
    ref = np.asarray(prefill_attention(q, k, v, lengths))
    check_valid(out, ref, np.asarray(lengths))


def test_ring_gqa_with_tp_sharded_heads():
    """tp=2 shards 8 query heads and 2 KV heads; sp=2 rides the ring; KV
    blocks stay at width 1 per device."""
    mesh = make_mesh(MeshConfig(dp=2, sp=2, tp=2))
    b, h, n_kv, s, hd = 2, 8, 2, 32, 16
    q = rand(3, (b, h, s, hd))
    k = rand(4, (b, n_kv, s, hd))
    v = rand(5, (b, n_kv, s, hd))
    lengths = jnp.asarray([32, 32], jnp.int32)
    out = np.asarray(ring_prefill_attention(q, k, v, lengths, mesh))
    ref = np.asarray(prefill_attention(q, k, v, lengths))
    check_valid(out, ref, np.asarray(lengths))


def test_ring_gqa_kv_heads_not_divisible_by_tp():
    """2 KV heads on tp=4: KV (and therefore q's grouping) replicate over tp
    instead of failing."""
    mesh = make_mesh(MeshConfig(sp=2, tp=4))
    b, h, n_kv, s, hd = 1, 8, 2, 32, 16
    q = rand(6, (b, h, s, hd))
    k = rand(7, (b, n_kv, s, hd))
    v = rand(8, (b, n_kv, s, hd))
    lengths = jnp.asarray([32], jnp.int32)
    out = np.asarray(ring_prefill_attention(q, k, v, lengths, mesh))
    ref = np.asarray(prefill_attention(q, k, v, lengths))
    check_valid(out, ref, np.asarray(lengths))


def test_engine_serves_through_ring_attention():
    """Serving-path sequence parallelism (SURVEY §5.7): an engine on an
    sp-mesh admits prompts through ring-attention prefill and generates the
    same tokens as the single-device engine."""
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = resolve_spec("llama-tiny", {"n_kv_heads": "4"})
    prompt = [(5 + 3 * i) % 500 for i in range(60)]
    eng_1 = InferenceEngine(spec, decode_chunk=4, n_slots=2)
    eng_sp = InferenceEngine(spec, make_mesh(MeshConfig(sp=4, tp=2)),
                             decode_chunk=4, n_slots=2)
    assert eng_sp._use_sp and eng_sp.prefill_chunk == 0
    for sampler, seed in ((SamplerConfig(temperature=0.0), 0),
                          (SamplerConfig(temperature=0.8, top_p=0.9), 7)):
        one = eng_1.generate(prompt, max_new_tokens=10, sampler=sampler,
                             seed=seed).token_ids
        sp_toks = eng_sp.generate(prompt, max_new_tokens=10, sampler=sampler,
                                  seed=seed).token_ids
        assert sp_toks == one


def test_tpu_backend_sp_url():
    """tpu://…&sp=N builds an sp-mesh engine and serves through it."""
    import asyncio

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    b = TpuBackend.from_spec(BackendSpec(
        name="sp", url="tpu://llama-tiny?n_kv_heads=4&sp=4&tp=2&seed=2",
        model="t"))
    assert b.engine._use_sp
    body = {"model": "t", "messages": [{"role": "user", "content": "hello " * 30}],
            "max_tokens": 6}
    res = asyncio.run(b.complete(body, {}, timeout=120))
    assert res.status_code == 200
    assert res.body["usage"]["completion_tokens"] >= 1


def test_forward_logits_sp_matches_dense():
    """The full sequence-parallel model forward (ring attention per layer,
    GQA, under jit on a dp2×sp2×tp2 mesh) matches the dense forward."""
    from quorum_tpu.models.init import init_params
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.models.transformer import forward_logits, forward_logits_sp

    spec = resolve_spec("llama-tiny", {"max_seq": "64", "dtype": "float32"})
    params = init_params(spec, seed=0)
    mesh = make_mesh(MeshConfig(dp=2, sp=2, tp=2))
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 1, spec.vocab_size)
    lengths = jnp.asarray([32, 20], jnp.int32)

    dense = forward_logits(params, spec, tokens)
    sp_out = jax.jit(
        lambda p, t, l: forward_logits_sp(p, spec, t, l, mesh)
    )(params, tokens, lengths)
    dense, sp_out = np.asarray(dense), np.asarray(sp_out)
    # dense forward has no length mask; compare valid rows only
    for bi, n in enumerate([32, 20]):
        np.testing.assert_allclose(
            sp_out[bi, :n], dense[bi, :n], atol=2e-4, rtol=1e-3
        )
