"""Ring attention vs full attention on the virtual 8-device CPU mesh.

Sequence sharded over sp; batch over dp; heads over tp — only sp
communicates (ppermute per ring step). Reference: the XLA-native
prefill_attention, itself validated against transformers' forward.
"""

import numpy as np

import jax
import jax.numpy as jnp

from quorum_tpu.ops.attention import prefill_attention
from quorum_tpu.parallel.mesh import MeshConfig, make_mesh
from quorum_tpu.parallel.ring_attention import ring_prefill_attention


def rand(seed, shape):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)


def run_case(mesh_cfg, b, h, s, hd, lengths):
    mesh = make_mesh(mesh_cfg)
    q = rand(0, (b, h, s, hd))
    k = rand(1, (b, h, s, hd))
    v = rand(2, (b, h, s, hd))
    lengths = jnp.asarray(lengths, jnp.int32)
    out = ring_prefill_attention(q, k, v, lengths, mesh)
    ref = prefill_attention(q, k, v, lengths)
    return np.asarray(out), np.asarray(ref), np.asarray(lengths)


def check_valid(out, ref, lengths, atol=2e-5):
    for bi, n in enumerate(lengths):
        np.testing.assert_allclose(
            out[bi, :, :n, :], ref[bi, :, :n, :], atol=atol, rtol=1e-4
        )


def test_ring_sp4_matches_full():
    out, ref, lengths = run_case(MeshConfig(sp=4), 1, 2, 64, 16, [64])
    check_valid(out, ref, lengths)


def test_ring_sp8_long_sequence():
    out, ref, lengths = run_case(MeshConfig(sp=8), 1, 2, 128, 16, [128])
    check_valid(out, ref, lengths)


def test_ring_composes_with_dp_and_tp():
    """Full dp2 × sp2 × tp2 mesh: batch and heads shard too; only the ring
    communicates across sp."""
    out, ref, lengths = run_case(MeshConfig(dp=2, sp=2, tp=2), 2, 2, 64, 16, [64, 64])
    check_valid(out, ref, lengths)


def test_ring_respects_lengths():
    out, ref, lengths = run_case(MeshConfig(sp=4), 2, 2, 64, 16, [30, 55])
    check_valid(out, ref, lengths)
    assert not np.isnan(out).any()


def test_forward_logits_sp_matches_dense():
    """The full sequence-parallel model forward (ring attention per layer,
    GQA, under jit on a dp2×sp2×tp2 mesh) matches the dense forward."""
    from quorum_tpu.models.init import init_params
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.models.transformer import forward_logits, forward_logits_sp

    spec = resolve_spec("llama-tiny", {"max_seq": "64", "dtype": "float32"})
    params = init_params(spec, seed=0)
    mesh = make_mesh(MeshConfig(dp=2, sp=2, tp=2))
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 1, spec.vocab_size)
    lengths = jnp.asarray([32, 20], jnp.int32)

    dense = forward_logits(params, spec, tokens)
    sp_out = jax.jit(
        lambda p, t, l: forward_logits_sp(p, spec, t, l, mesh)
    )(params, tokens, lengths)
    dense, sp_out = np.asarray(dense), np.asarray(sp_out)
    # dense forward has no length mask; compare valid rows only
    for bi, n in enumerate([32, 20]):
        np.testing.assert_allclose(
            sp_out[bi, :n], dense[bi, :n], atol=2e-4, rtol=1e-3
        )
