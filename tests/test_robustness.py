"""Fault-contained serving (docs/robustness.md): the fault-injection
registry, request deadlines, engine failure containment + breaker,
truthful health, Retry-After contracts, and the HTTP retry ladder.

Fast tier: registry/breaker/retry/config units plus app-level health and
contract checks over a tiny llama engine (the test_metrics pattern).
Engine-heavy scenarios (dispatch-failure containment, deadline sweeps,
mid-stream disconnect, the chaos smoke) are slow-tier."""

import asyncio
import threading
import time

import httpx
import pytest

from quorum_tpu import faults
from tests.conftest import make_client

AUTH = {"Authorization": "Bearer t"}


def teardown_function(_fn):
    faults.disarm()  # no test may leak an armed site into the next


# ---- fault registry (no jax, no server) ------------------------------------


def test_faults_arm_fire_autodisarm():
    faults.reset_counts()
    assert faults.fire is faults._noop  # disarmed = literal no-op binding
    faults.arm("engine.decode", times=2)
    assert faults.armed("engine.decode")
    for _ in range(2):
        with pytest.raises(faults.FaultInjected) as ei:
            faults.fire("engine.decode")
        assert ei.value.site == "engine.decode"
    # auto-disarmed after `times` fires; the binding reverts to the no-op
    faults.fire("engine.decode")
    assert not faults.armed()
    assert faults.fire is faults._noop
    assert faults.fired("engine.decode") == 2


def test_faults_reject_unknown_site_and_bad_times():
    with pytest.raises(ValueError):
        faults.arm("engine.nonexistent")
    with pytest.raises(ValueError):
        faults.arm("engine.decode", times=0)


def test_faults_delay_mode_sleeps_instead_of_raising():
    faults.arm("engine.decode", times=1, delay=0.05)
    t0 = time.perf_counter()
    faults.fire("engine.decode")  # must NOT raise
    assert time.perf_counter() - t0 >= 0.04
    assert not faults.armed()


def test_faults_custom_exception():
    faults.arm("http.request", exc=lambda site: RuntimeError(site))
    with pytest.raises(RuntimeError):
        faults.fire("http.request")


# ---- breaker unit ----------------------------------------------------------


def test_breaker_opens_after_threshold_and_probes():
    from quorum_tpu.engine.engine import _Breaker

    b = _Breaker(threshold=2, window=10.0, cooldown=1.0)
    assert b.state == "closed" and b.allow(now=0.0)
    b.record_failure(now=0.0)
    assert b.state == "closed" and b.allow(now=0.1)
    b.record_failure(now=0.2)
    assert b.state == "open"
    assert not b.allow(now=0.5)
    assert b.retry_after(now=0.5) == pytest.approx(0.7)
    # cooldown elapsed: exactly one probe per cooldown interval
    assert b.allow(now=1.5)
    assert b.state == "half_open"
    assert not b.allow(now=1.6)        # probe outstanding
    assert b.allow(now=2.6)            # probe stamp expired: a new probe
    b.record_success()
    assert b.state == "closed" and b.allow(now=2.7)


def test_breaker_failure_while_half_open_reopens():
    from quorum_tpu.engine.engine import _Breaker

    b = _Breaker(threshold=1, window=10.0, cooldown=1.0)
    b.record_failure(now=0.0)
    assert b.state == "open"
    assert b.allow(now=1.5)            # half-open probe
    b.record_failure(now=1.6)          # probe's admission failed
    assert b.state == "open"
    assert not b.allow(now=1.7)


def test_breaker_window_prunes_stale_failures():
    from quorum_tpu.engine.engine import _Breaker

    b = _Breaker(threshold=2, window=1.0, cooldown=1.0)
    b.record_failure(now=0.0)
    b.record_failure(now=5.0)          # first failure long out of window
    assert b.state == "closed"


# ---- HTTP retry ladder -----------------------------------------------------


def _flaky_backend(fails: int, *, status: int = 500, retries: int,
                   exc: Exception | None = None):
    from quorum_tpu.backends.http_backend import HttpBackend

    calls = {"n": 0}

    def handler(req: httpx.Request) -> httpx.Response:
        calls["n"] += 1
        if calls["n"] <= fails:
            if exc is not None:
                raise exc
            return httpx.Response(status, json={"error": {
                "message": "transient", "type": "server_error"}})
        return httpx.Response(200, json={
            "choices": [{"message": {"role": "assistant", "content": "ok"}}]})

    hb = HttpBackend(
        "flaky", "http://u.test/v1", "m", retries=retries,
        client=httpx.AsyncClient(transport=httpx.MockTransport(handler)))
    return hb, calls


async def test_http_retry_recovers_from_5xx():
    from quorum_tpu.observability import BACKEND_RETRIES

    hb, calls = _flaky_backend(2, retries=2)
    before = BACKEND_RETRIES.value_of(backend="flaky")
    result = await hb.complete({"messages": []}, AUTH, 10.0)
    assert result.status_code == 200 and calls["n"] == 3
    assert BACKEND_RETRIES.value_of(backend="flaky") == before + 2


async def test_http_retry_recovers_from_connect_error():
    hb, calls = _flaky_backend(
        1, retries=1, exc=httpx.ConnectError("refused"))
    result = await hb.complete({"messages": []}, AUTH, 10.0)
    assert result.status_code == 200 and calls["n"] == 2


async def test_http_retry_honors_upstream_retry_after():
    """A 503 upstream that names its recovery window (Retry-After) is not
    re-POSTed inside it — the header floors the backoff delay."""
    from quorum_tpu.backends.http_backend import HttpBackend

    calls = {"n": 0}

    def handler(req: httpx.Request) -> httpx.Response:
        calls["n"] += 1
        if calls["n"] == 1:
            return httpx.Response(
                503, headers={"Retry-After": "0.3"},
                json={"error": {"message": "shedding",
                                "type": "overloaded_error"}})
        return httpx.Response(200, json={
            "choices": [{"message": {"role": "assistant", "content": "ok"}}]})

    hb = HttpBackend(
        "polite", "http://u.test/v1", "m", retries=2,
        client=httpx.AsyncClient(transport=httpx.MockTransport(handler)))
    t0 = time.perf_counter()
    result = await hb.complete({"messages": []}, AUTH, 10.0)
    assert result.status_code == 200 and calls["n"] == 2
    assert time.perf_counter() - t0 >= 0.3  # waited out the upstream's ask


async def test_http_no_retry_by_default():
    hb, calls = _flaky_backend(1, retries=0)
    result = await hb.complete({"messages": []}, AUTH, 10.0)
    assert result.status_code == 500 and calls["n"] == 1


async def test_http_retry_budget_exhausts_to_upstream_error():
    hb, calls = _flaky_backend(99, retries=2)
    result = await hb.complete({"messages": []}, AUTH, 10.0)
    assert result.status_code == 500 and calls["n"] == 3


async def test_http_retry_never_sleeps_past_deadline():
    from quorum_tpu.backends.base import BackendError

    hb, calls = _flaky_backend(
        99, retries=50, exc=httpx.ConnectError("refused"))
    t0 = time.perf_counter()
    with pytest.raises(BackendError):
        await hb.complete({"messages": []}, AUTH, 0.05)
    assert time.perf_counter() - t0 < 2.0  # not 50 backoff sleeps


async def test_http_stream_retries_before_first_byte():
    """The streaming retry gap, pinned (docs/robustness.md): ``retries:``
    applies only BEFORE the first byte is relayed. Connect errors and
    pre-stream 5xx on streaming calls ARE retried — the router tier's
    failover pacing leans on this — while an open 2xx stream never
    retries (next test), so tokens cannot double-deliver."""
    from quorum_tpu.backends.http_backend import HttpBackend

    calls = {"n": 0}

    def handler(req):
        calls["n"] += 1
        if calls["n"] == 1:
            raise httpx.ConnectError("refused")
        if calls["n"] == 2:
            return httpx.Response(503, json={"error": {
                "message": "shedding", "type": "overloaded_error"}})
        return httpx.Response(
            200, headers={"content-type": "text/event-stream"},
            content=(b'data: {"choices":[{"delta":{"content":"ok"}}]}\n\n'
                     b"data: [DONE]\n\n"))

    hb = HttpBackend(
        "s", "http://u.test/v1", "m", retries=3,
        client=httpx.AsyncClient(transport=httpx.MockTransport(handler)))
    events = [e async for e in hb.stream({"messages": []}, AUTH, 10.0)]
    assert calls["n"] == 3  # connect error + 503 both retried pre-stream
    assert len(events) == 1
    assert events[0]["choices"][0]["delta"]["content"] == "ok"


async def test_http_stream_never_retries_after_first_byte():
    """Once a 2xx stream is open, a mid-stream failure SURFACES — a
    second attempt could double-deliver tokens already on the client's
    wire. The upstream is called exactly once."""
    from quorum_tpu.backends.base import BackendError
    from quorum_tpu.backends.http_backend import HttpBackend

    calls = {"n": 0}

    class _Explodes(httpx.AsyncByteStream):
        async def __aiter__(self):
            yield b'data: {"choices":[{"delta":{"content":"tok"}}]}\n\n'
            raise httpx.ReadError("connection reset mid-body")

    def handler(req):
        calls["n"] += 1
        return httpx.Response(
            200, headers={"content-type": "text/event-stream"},
            stream=_Explodes())

    hb = HttpBackend(
        "s", "http://u.test/v1", "m", retries=3,
        client=httpx.AsyncClient(transport=httpx.MockTransport(handler)))
    got = []
    with pytest.raises(BackendError):
        async for e in hb.stream({"messages": []}, AUTH, 10.0):
            got.append(e)
    assert calls["n"] == 1  # never re-POSTed
    assert len(got) == 1    # the relayed token arrived exactly once


async def test_http_stream_error_keeps_retry_after_header():
    """A pre-stream 503's Retry-After rides the BackendError (the header
    contract, docs/robustness.md) — the router's terminal relay must pace
    streaming clients exactly like non-streaming ones."""
    from quorum_tpu.backends.base import BackendError
    from quorum_tpu.backends.http_backend import HttpBackend

    def handler(req):
        return httpx.Response(
            503, headers={"Retry-After": "7"},
            json={"error": {"message": "shedding",
                            "type": "overloaded_error"}})

    hb = HttpBackend(
        "s", "http://u.test/v1", "m",
        client=httpx.AsyncClient(transport=httpx.MockTransport(handler)))
    with pytest.raises(BackendError) as exc:
        async for _ in hb.stream({"messages": []}, AUTH, 5.0):
            pass
    assert exc.value.status_code == 503
    assert exc.value.headers.get("Retry-After") == "7"


async def test_http_stream_no_retry_by_default():
    from quorum_tpu.backends.base import BackendError
    from quorum_tpu.backends.http_backend import HttpBackend

    calls = {"n": 0}

    def handler(req):
        calls["n"] += 1
        raise httpx.ConnectError("refused")

    hb = HttpBackend(
        "s", "http://u.test/v1", "m",
        client=httpx.AsyncClient(transport=httpx.MockTransport(handler)))
    with pytest.raises(BackendError):
        async for _ in hb.stream({"messages": []}, AUTH, 5.0):
            pass
    assert calls["n"] == 1


def test_retry_after_parses_both_rfc9110_forms():
    """Satellite (ISSUE 13): Retry-After comes in delay-seconds AND
    HTTP-date forms; the date form must parse (not silently read as 0.0)
    and negative/past values clamp to 0 — the router paces failover on
    this value."""
    from email.utils import format_datetime
    from datetime import datetime, timedelta, timezone

    from quorum_tpu.backends.http_backend import HttpBackend

    def resp(value: str | None):
        headers = {} if value is None else {"Retry-After": value}
        return httpx.Response(503, headers=headers)

    # numeric form
    assert HttpBackend._retry_after_s(resp("2")) == 2.0
    assert HttpBackend._retry_after_s(resp("1.5")) == 1.5
    assert HttpBackend._retry_after_s(resp("-3")) == 0.0  # clamped
    # HTTP-date form: ~60s ahead parses to ~60s from now
    future = datetime.now(timezone.utc) + timedelta(seconds=60)
    got = HttpBackend._retry_after_s(resp(format_datetime(future,
                                                          usegmt=True)))
    assert 50.0 < got <= 61.0, got
    # a date in the past clamps to 0 (no ask), as does garbage/absence
    past = datetime.now(timezone.utc) - timedelta(seconds=60)
    assert HttpBackend._retry_after_s(resp(format_datetime(past,
                                                           usegmt=True))) == 0.0
    assert HttpBackend._retry_after_s(resp("soonish")) == 0.0
    assert HttpBackend._retry_after_s(resp(None)) == 0.0


async def test_http_retry_honors_date_form_retry_after():
    """The 5xx retry floor reads the HTTP-date form too: a 503 naming a
    recovery window ~0.3s out is not re-POSTed inside it."""
    from email.utils import format_datetime
    from datetime import datetime, timedelta, timezone

    from quorum_tpu.backends.http_backend import HttpBackend

    calls = {"n": 0}

    def handler(req: httpx.Request) -> httpx.Response:
        calls["n"] += 1
        if calls["n"] == 1:
            when = datetime.now(timezone.utc) + timedelta(seconds=1)
            return httpx.Response(
                503, headers={"Retry-After": format_datetime(
                    when, usegmt=True)},
                json={"error": {"message": "shedding",
                                "type": "overloaded_error"}})
        return httpx.Response(200, json={
            "choices": [{"message": {"role": "assistant",
                                     "content": "ok"}}]})

    hb = HttpBackend(
        "polite", "http://u.test/v1", "m", retries=2,
        client=httpx.AsyncClient(transport=httpx.MockTransport(handler)))
    t0 = time.perf_counter()
    result = await hb.complete({"messages": []}, AUTH, 10.0)
    assert result.status_code == 200 and calls["n"] == 2
    # waited at least most of the named window (date resolution is 1s,
    # so the floor lands anywhere in (0, 1]; it must not re-POST
    # immediately)
    assert time.perf_counter() - t0 >= 0.05


def test_config_parses_retries():
    from quorum_tpu.config import BackendSpec

    assert BackendSpec.from_dict({"name": "a", "url": "http://x"}).retries == 0
    assert BackendSpec.from_dict(
        {"name": "a", "url": "http://x", "retries": 3}).retries == 3
    assert BackendSpec.from_dict(
        {"name": "a", "url": "http://x", "retries": "junk"}).retries == 0
    assert BackendSpec.from_dict(
        {"name": "a", "url": "http://x", "retries": -2}).retries == 0


# ---- request-level contracts ----------------------------------------------


def test_timeout_body_knob_validation():
    from quorum_tpu.oai import validate_request_body

    ok = {"messages": [], "timeout": 1.5}
    assert validate_request_body(ok) is None
    for bad in (0, -1, "fast", True, float("inf")):
        msg = validate_request_body({"messages": [], "timeout": bad})
        assert msg is not None and "timeout" in msg


def test_overload_errors_carry_retry_after():
    from quorum_tpu.backends.tpu_backend import (
        _breaker_open, _deadline_error, _overloaded, _timeout_error)
    from quorum_tpu.engine.engine import DeadlineExceeded, EngineBreakerOpen

    assert _overloaded("x").headers["Retry-After"] == "1"
    assert _overloaded("x", retry_after=4.2).headers["Retry-After"] == "5"
    e = _breaker_open("x", EngineBreakerOpen(3.0))
    assert e.status_code == 503 and e.headers["Retry-After"] == "3"
    shed = _deadline_error("x", DeadlineExceeded("queue"))
    assert shed.status_code == 503 and "Retry-After" in shed.headers
    late = _deadline_error("x", DeadlineExceeded("decode"))
    assert late.status_code == 504
    assert late.body["error"]["type"] == "timeout_error"
    assert _timeout_error("x", 1.0).status_code == 504


async def test_relayed_503_keeps_retry_after_header():
    """The server relays a backend's typed 503 verbatim INCLUDING its
    Retry-After header (the contract load balancers key on)."""
    from quorum_tpu.backends.base import BackendError
    from quorum_tpu.oai import error_body

    class Overloaded:
        name = "O"
        model = "m"
        requires_auth = False

        async def complete(self, body, headers, timeout):
            raise BackendError(
                "overloaded", status_code=503,
                body=error_body("overloaded", type_="overloaded_error",
                                code=503),
                headers={"Retry-After": "7"})

        async def aclose(self):
            return None

    cfg = {"settings": {"timeout": 5},
           "primary_backends": [{"name": "O", "url": "http://o.test/v1",
                                 "model": "m"}]}
    async with make_client(cfg, O=Overloaded()) as client:
        r = await client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "hi"}]},
            headers=AUTH)
        assert r.status_code == 503
        assert r.headers["Retry-After"] == "7"
        assert r.json()["error"]["type"] == "overloaded_error"


# ---- app-level health over a real tiny engine ------------------------------


def _tpu_config(seed: int = 0):
    return {
        "settings": {"timeout": 30},
        "primary_backends": [
            {"name": "T",
             "url": f"tpu://llama-tiny?seed={9200 + seed}&slots=2",
             "model": "t"},
        ],
    }


async def test_health_truthful_and_ready():
    async with make_client(_tpu_config(0)) as client:
        engine = None
        r = await client.get("/health")
        body = r.json()
        assert r.status_code == 200 and body["status"] == "healthy"
        row = body["checks"][0]
        assert row["scheduler_alive"] and row["breaker"] == "closed"
        assert (await client.get("/ready")).status_code == 200

        # Reach the engine through the live registry to flip real signals.
        from quorum_tpu.server.app import create_app  # noqa: F401
        transport = client._transport
        engine = transport.app.state["registry"].get("T").engine
        for _ in range(3):
            engine.breaker.record_failure()
        assert engine.breaker.state == "open"
        body = (await client.get("/health")).json()
        assert body["status"] == "degraded"
        ready = await client.get("/ready")
        assert ready.status_code == 503
        assert ready.json()["reason"] == "degraded"
        assert "retry-after" in {k.lower() for k in ready.headers}
        engine.breaker.record_success()
        assert (await client.get("/health")).json()["status"] == "healthy"


async def test_health_unhealthy_when_scheduler_dead():
    async with make_client(_tpu_config(1)) as client:
        engine = client._transport.app.state["registry"].get("T").engine
        engine.shutdown()
        r = await client.get("/health")
        assert r.status_code == 503
        assert r.json()["status"] == "unhealthy"
        assert (await client.get("/ready")).status_code == 503


async def test_metrics_expose_robustness_families():
    async with make_client(_tpu_config(2)) as client:
        text = (await client.get("/metrics")).text
        assert "# TYPE quorum_tpu_engine_rebuilds_total counter" in text
        assert ("# TYPE quorum_tpu_engine_deadline_exceeded_total counter"
                in text)
        assert "# TYPE quorum_tpu_engine_breaker_state gauge" in text
        assert 'quorum_tpu_engine_breaker_state{backend="T"} 0' in text
        assert "# TYPE quorum_tpu_deadline_exceeded_total counter" in text
        assert "# TYPE quorum_tpu_backend_retries_total counter" in text


# ---- engine-level containment & deadlines (slow tier) ----------------------


def _engine(**kw):
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import MODEL_PRESETS

    kw.setdefault("decode_chunk", 4)
    kw.setdefault("n_slots", 2)
    return InferenceEngine(MODEL_PRESETS["llama-tiny"], **kw)


def _greedy(eng, prompt, n=6, **kw):
    from quorum_tpu.ops.sampling import SamplerConfig

    return eng.generate(prompt, max_new_tokens=n,
                        sampler=SamplerConfig(temperature=0.0), **kw)


@pytest.mark.slow
def test_queued_request_survives_anothers_dispatch_failure():
    """The _fail_all blast-radius regression: a decode-dispatch failure
    dooms the admitted request but a never-dispatched pending request is
    requeued — it completes with exactly the tokens of an undisturbed
    run."""
    from quorum_tpu.ops.sampling import SamplerConfig

    eng = _engine(n_slots=1)
    baseline = _greedy(eng, [7, 8, 9], n=6).token_ids
    faults.arm("engine.decode", times=1)
    victim = eng.submit([3, 4, 5], max_new_tokens=8,
                        sampler=SamplerConfig(temperature=0.0))
    survivor = eng.submit([7, 8, 9], max_new_tokens=6,
                          sampler=SamplerConfig(temperature=0.0))
    with pytest.raises(faults.FaultInjected):
        list(eng.stream_results(victim))
    out = list(eng.stream_results(survivor))
    assert out == baseline
    assert eng.n_rebuilds == 1
    eng.shutdown()


@pytest.mark.slow
def test_admission_failure_spares_active_and_pending():
    """A poisoned request's own admission dispatch (state intact) dooms
    only that request: no rebuild, and the engine keeps serving."""
    eng = _engine(n_slots=2)
    baseline = _greedy(eng, [5, 6], n=5).token_ids
    faults.arm("engine.admit", times=1)
    with pytest.raises(faults.FaultInjected):
        _greedy(eng, [1, 2, 3], n=4)
    assert eng.n_rebuilds == 0  # contained without touching shared state
    assert _greedy(eng, [5, 6], n=5).token_ids == baseline
    assert eng.breaker.state == "closed"
    eng.shutdown()


@pytest.mark.slow
def test_deadline_queue_shed_and_decode_cancel():
    from quorum_tpu.engine.engine import DeadlineExceeded
    from quorum_tpu.ops.sampling import SamplerConfig

    eng = _engine(n_slots=1)
    _greedy(eng, [1, 2], n=4)  # warm programs so sweep cadence is real
    # Latency injection makes the blocker slow deterministically.
    faults.arm("engine.decode", times=100000, delay=0.02)
    try:
        blocker = eng.submit([1, 2, 3], max_new_tokens=64,
                             sampler=SamplerConfig(temperature=0.0))
        late = eng.submit([9, 9], max_new_tokens=4,
                          sampler=SamplerConfig(temperature=0.0),
                          deadline=time.monotonic() + 0.15)
        with pytest.raises(DeadlineExceeded) as ei:
            list(eng.stream_results(late))
        assert ei.value.stage == "queue"
        blocker.cancel.set()
        # Admitted request whose deadline passes mid-decode: stage decode,
        # and the slot frees for the follow-up.
        slow = eng.submit([4, 5, 6], max_new_tokens=64,
                          sampler=SamplerConfig(temperature=0.0),
                          deadline=time.monotonic() + 0.2)
        with pytest.raises(DeadlineExceeded) as ei:
            list(eng.stream_results(slow))
        assert ei.value.stage in ("prefill", "decode")
    finally:
        faults.disarm()
    assert len(_greedy(eng, [5, 5], n=3).token_ids) == 3  # slot released
    assert eng.n_deadline_exceeded == 2
    eng.shutdown()


@pytest.mark.slow
def test_expired_deadline_sheds_at_submit():
    from quorum_tpu.engine.engine import DeadlineExceeded
    from quorum_tpu.ops.sampling import SamplerConfig

    eng = _engine()
    with pytest.raises(DeadlineExceeded):
        eng.submit([1, 2], max_new_tokens=4,
                   sampler=SamplerConfig(temperature=0.0),
                   deadline=time.monotonic() - 1.0)
    eng.shutdown()


@pytest.mark.slow
async def test_client_disconnect_mid_sse_frees_slot():
    """GeneratorExit during SSE (client gone) cancels the engine request
    within one decode chunk: the slot frees and cancellations_total
    counts it."""
    async with make_client(_tpu_config(3)) as client:
        backend = client._transport.app.state["registry"].get("T")
        engine = backend.engine
        body = {"model": "t", "stream": True, "max_tokens": 512,
                "logit_bias": {str(backend.tokenizer.eos_id): -100},
                "messages": [{"role": "user", "content": "go"}]}
        cancelled_before = engine.n_cancelled
        agen = backend.stream(body, AUTH, 30.0)
        got = await agen.__anext__()           # role chunk: stream is live
        assert got["choices"][0]["delta"].get("role") == "assistant"
        while engine.metrics()["busy_slots"] == 0:
            await asyncio.sleep(0.01)
        await agen.aclose()                    # GeneratorExit into the gen
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            m = engine.metrics()
            if (m["busy_slots"] == 0 and m["admitting"] == 0
                    and m["cancellations_total"] > cancelled_before):
                break
            await asyncio.sleep(0.02)
        m = engine.metrics()
        assert m["busy_slots"] == 0 and m["admitting"] == 0
        assert m["cancellations_total"] > cancelled_before


@pytest.mark.slow
def test_breaker_storm_opens_and_probe_recovers():
    eng = _engine()
    eng.breaker.threshold = 2
    eng.breaker.cooldown = 0.3
    baseline = _greedy(eng, [3, 4, 5], n=6).token_ids
    for _ in range(2):
        faults.arm("engine.decode", times=1)
        with pytest.raises(Exception):
            _greedy(eng, [6, 7], n=8)
        faults.disarm()
    assert eng.breaker.state == "open"
    from quorum_tpu.engine.engine import EngineBreakerOpen
    from quorum_tpu.ops.sampling import SamplerConfig

    with pytest.raises(EngineBreakerOpen):
        eng.submit([1, 1], max_new_tokens=2,
                   sampler=SamplerConfig(temperature=0.0))
    time.sleep(0.35)
    assert _greedy(eng, [3, 4, 5], n=6).token_ids == baseline  # the probe
    assert eng.breaker.state == "closed"
    eng.shutdown()


# ---- chaos harness smoke ---------------------------------------------------


@pytest.mark.slow
def test_chaos_check_quick_subset():
    """The suite's smoke over the same entry point `make chaos-check`
    runs (reduced sweep: one injection site, queue deadline, breaker,
    pinning, http retry)."""
    import importlib

    mod = importlib.import_module("chaos_check")
    out = mod.run(quick=True)
    assert out["failed"] == 0, out["failures"]


def _import_scripts_path():
    import os
    import sys

    scripts = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)


_import_scripts_path()
