"""Multi-replica router tier (quorum_tpu/router/, docs/scaling.md).

Fast tier: ring/affinity/wire units, store export/import, and the router
app end-to-end over jax-free fake replicas on real sockets (placement
stability, failover, rotation, migration warmth, metrics). Slow tier: the
prefix-migration round trip between two REAL engines — a chunk chain
serialized from engine A and seeded into engine B produces a tier-hit
restore on B with outputs pinned vs cold prefill — plus the server's
GET/PUT /debug/prefix/chunks routes over a live tpu:// backend.
"""

import asyncio

import httpx
import numpy as np
import pytest

from quorum_tpu.cache import prefix_wire
from quorum_tpu.cache.prefix_store import PrefixStore
from quorum_tpu.router import affinity
from quorum_tpu.router.app import RouterConfig, create_router_app
from quorum_tpu.router.fake_replica import (
    FakeReplicaState,
    create_fake_replica_app,
)
from quorum_tpu.router.ring import BoundedLoadRing, hash_key

slow = pytest.mark.slow


# ---- ring -------------------------------------------------------------------


def test_ring_placement_is_deterministic_and_spreads():
    ring = BoundedLoadRing()
    for n in ("a", "b", "c", "d"):
        ring.add(n)
    keys = [hash_key(f"conversation-{i}".encode()) for i in range(400)]
    first = [ring.primary(k) for k in keys]
    assert first == [ring.primary(k) for k in keys]  # deterministic
    counts = {n: first.count(n) for n in ("a", "b", "c", "d")}
    assert all(c > 0 for c in counts.values()), counts  # everyone serves


def test_ring_remove_only_remaps_departed_keys():
    ring = BoundedLoadRing()
    for n in ("a", "b", "c", "d"):
        ring.add(n)
    keys = [hash_key(f"conversation-{i}".encode()) for i in range(400)]
    before = {k: ring.primary(k) for k in keys}
    ring.remove("c")
    after = {k: ring.primary(k) for k in keys}
    moved = [k for k in keys if before[k] != after[k]]
    # every moved key belonged to the departed replica — nobody else's
    # conversations cold-start (the consistent-hashing property)
    assert moved and all(before[k] == "c" for k in moved)
    assert all(after[k] != "c" for k in keys)
    # rejoining restores the original placement exactly
    ring.add("c")
    assert {k: ring.primary(k) for k in keys} == before


def test_ring_candidates_order_and_bounded_load():
    ring = BoundedLoadRing(load_factor=1.25)
    for n in ("a", "b", "c"):
        ring.add(n)
    key = hash_key(b"some conversation")
    order = ring.candidates(key)
    assert order[0] == ring.primary(key)
    assert sorted(order) == ["a", "b", "c"]
    # uniform load: nothing demoted
    assert ring.candidates(key, {n: 2 for n in "abc"}) == order
    # the primary far past capacity is demoted to the tail — the key
    # spills for THIS request, membership untouched
    hot = order[0]
    loaded = ring.candidates(key, {n: (50 if n == hot else 0)
                                   for n in "abc"})
    assert loaded[-1] == hot and set(loaded) == set(order)
    assert ring.primary(key) == hot


def test_ring_validation():
    with pytest.raises(ValueError):
        BoundedLoadRing(vnodes=0)
    with pytest.raises(ValueError):
        BoundedLoadRing(load_factor=0.5)
    assert BoundedLoadRing().candidates(123) == []


# ---- affinity keys ----------------------------------------------------------


def _turns(conv: str, n: int) -> list[dict]:
    """n bodies of one growing conversation (client-appended history)."""
    msgs = [{"role": "user", "content": conv}]
    out = [{"messages": list(msgs)}]
    for t in range(n - 1):
        msgs = msgs + [{"role": "assistant", "content": f"answer {t}"},
                       {"role": "user", "content": f"follow-up {t}"}]
        out.append({"messages": list(msgs)})
    return out


def test_affinity_key_stable_across_turns():
    for conv in ("hi", "a much longer opening question that spans "
                 "well past one affinity chunk of byte tokens, with "
                 "plenty of additional prose to be sure"):
        keys = {affinity.conversation_key(b) for b in _turns(conv, 4)}
        assert len(keys) == 1, conv


def test_affinity_key_distinguishes_conversations():
    keys = {affinity.conversation_key(
        {"messages": [{"role": "user", "content": f"conversation {i}"}]})
        for i in range(50)}
    assert len(keys) == 50


def test_affinity_system_prompt_rides_the_key():
    sys_a = [{"role": "system", "content": "persona A"},
             {"role": "user", "content": "same question"}]
    sys_b = [{"role": "system", "content": "persona B"},
             {"role": "user", "content": "same question"}]
    assert (affinity.conversation_key({"messages": sys_a})
            != affinity.conversation_key({"messages": sys_b}))


def test_affinity_head_is_prefix_of_full_render_and_chain_key_aligns():
    """The key'd head must be a byte-prefix of the full rendered prompt —
    that is what lets an exported chunk chain re-key to the same replica
    as the conversation that grew it (migration lands prefixes where the
    next turn routes)."""
    from quorum_tpu.engine.tokenizer import ByteTokenizer, render_chat

    tok = ByteTokenizer(259)
    for conv in ("hi",  # head far SHORTER than one affinity chunk
                 "an opening question long enough to cover the "
                 "affinity chunk comfortably, with extra prose "
                 "padding out the line"):
        bodies = _turns(conv, 3)
        head = affinity.conversation_tokens(bodies[0])
        for body in bodies:
            full = tok.encode(render_chat(body["messages"]))
            assert full[:len(head)] == head
            # a store chain additionally carries generated tokens past
            # the prompt — the key must still recover the head
            chain = full + tok.encode("generated reply text")
            assert (affinity.chain_key(chain)
                    == affinity.conversation_key(body)), conv


# ---- wire format ------------------------------------------------------------


def _chains(c: int = 4):
    mk = lambda tag: [np.full((2, 3, c), tag, np.int8),  # noqa: E731
                      np.arange(c, dtype=np.float32).reshape(1, 1, c)]
    return [
        ([1, 2, 3, 4, 5, 6, 7, 8], [mk(1), mk(2)]),
        ([9, 10, 11, 12], [mk(3)]),
    ]


def test_wire_round_trip():
    chains = _chains()
    blob = prefix_wire.serialize_chains(chains, 4)
    chunk_tokens, parsed = prefix_wire.parse(blob)
    assert chunk_tokens == 4 and len(parsed) == 2
    for (toks, pays), chain in zip(chains, parsed):
        assert chain.tokens == toks
        assert len(chain.payloads) == len(pays)
        for want, got in zip(pays, chain.payloads):
            for w, g in zip(want, got):
                assert w.dtype == g.dtype and w.shape == g.shape
                np.testing.assert_array_equal(w, g)
    # parsed arrays are copies, not views pinning the request body
    assert parsed[0].payloads[0][0].flags.owndata
    s = prefix_wire.stats(blob)
    assert s["chains"] == 2 and s["chunks"] == 3 and s["tokens"] == 12


def test_wire_rejects_malformed():
    import json as _json

    blob = prefix_wire.serialize_chains(_chains(), 4)
    with pytest.raises(prefix_wire.WireError):
        prefix_wire.parse(b"not a prefix payload")
    # crafted manifests raise WireError (→ 400), never a bare KeyError
    def crafted(chains):
        manifest = _json.dumps({"version": 1, "chunk_tokens": 4,
                                "chains": chains}).encode()
        return (prefix_wire.MAGIC
                + len(manifest).to_bytes(8, "big") + manifest)

    for chains in ([{"tokens": [1, 2, 3, 4]}],  # payload-less chunks
                   ["nonsense"],                # non-object chain
                   [{"tokens": [1, 2, 3, 4], "chunks": "x"}]):
        with pytest.raises(prefix_wire.WireError):
            prefix_wire.parse(crafted(chains))
    # a degenerate empty chain parses to nothing, harmlessly
    assert prefix_wire.parse(crafted([{"tokens": []}]))[1][0].tokens == []
    with pytest.raises(prefix_wire.WireError):
        prefix_wire.parse(blob[:20])  # truncated manifest
    # manifest length pointing past the payload
    bad = blob[: len(prefix_wire.MAGIC)] + (1 << 40).to_bytes(8, "big")
    with pytest.raises(prefix_wire.WireError):
        prefix_wire.parse(bad)
    # out-of-bounds array spec: truncate the payload region
    with pytest.raises(prefix_wire.WireError):
        prefix_wire.parse(blob[:-8])


# ---- store export / import --------------------------------------------------


def _payload(tag: int, c: int = 4):
    return [np.full((1, 1, c), tag % 127, np.int8)]


def test_store_export_chains_round_trips_through_import():
    src = PrefixStore(chunk_tokens=4, max_bytes=1 << 20)
    a = list(range(12))
    b = [50, 51, 52, 53]
    src.insert(a, 0, [_payload(1), _payload(2), _payload(3)])
    src.insert(b, 0, [_payload(4)])
    chains = src.export_chains()
    assert sorted(len(t) for t, _ in chains) == [4, 12]
    dst = PrefixStore(chunk_tokens=4, max_bytes=1 << 20)
    for toks, pays in chains:
        assert dst.import_chain(toks, pays) == len(toks)
    assert dst.covered(a) == 12 and dst.covered(b) == 4
    # import skips already-covered chunks (resident payloads win)
    assert dst.import_chain(a, [_payload(9)] * 3) == 0


def test_store_export_stops_at_evicted_ancestor():
    """Chunks beyond an evicted ancestor are unmatchable — the export must
    not ship bytes the importer could never restore."""
    s = PrefixStore(chunk_tokens=4, max_bytes=1 << 20)
    toks = list(range(12))
    s.insert(toks, 0, [_payload(1), _payload(2), _payload(3)])
    # evict the MIDDLE chunk by hand (the LRU normally drops tails first;
    # a mid-chain gap models a partially re-validated chain)
    node = s._root.children[tuple(toks[:4])].children[tuple(toks[4:8])]
    s._lru.pop(id(node))
    s.bytes_held -= node.entry.nbytes
    node.entry = None
    chains = s.export_chains()
    assert [len(t) for t in (c[0] for c in chains)] == [4]


def test_store_export_budget_and_lru_untouched():
    s = PrefixStore(chunk_tokens=4, max_bytes=1 << 20)
    s.insert(list(range(8)), 0, [_payload(1), _payload(2)])
    order_before = list(s._lru)
    assert s.export_chains(max_bytes=1) == []  # chain larger than budget
    assert list(s._lru) == order_before  # export never touches recency


def test_store_import_chain_validates_coverage():
    s = PrefixStore(chunk_tokens=4, max_bytes=1 << 20)
    with pytest.raises(ValueError):
        s.import_chain(list(range(8)), [_payload(1)])  # 2 chunks, 1 payload
    assert s.import_chain([1, 2], [_payload(1)]) == 0  # sub-chunk: nothing


# ---- router config ----------------------------------------------------------


def test_router_main_config_loading(tmp_path):
    """``python -m quorum_tpu.router`` config resolution: YAML file,
    --replicas override, CLI knob overrides."""
    from quorum_tpu.router.__main__ import load_router_config

    path = tmp_path / "router.yaml"
    path.write_text(
        "replicas:\n"
        "  - {name: cell-a, url: 'http://a:8000'}\n"
        "  - 'http://b:8000'\n"
        "policy: affinity\n"
        "ready_interval: 0.5\n")
    cfg = load_router_config(str(path), None)
    assert cfg.replicas == [("cell-a", "http://a:8000"),
                            ("replica-1", "http://b:8000")]
    assert cfg.ready_interval == 0.5
    # --replicas overrides the file's list; knob overrides apply
    cfg = load_router_config(str(path), "http://c:1,http://d:2",
                             policy="random", retries=3)
    assert [u for _, u in cfg.replicas] == ["http://c:1", "http://d:2"]
    assert cfg.policy == "random" and cfg.retries == 3
    with pytest.raises(ValueError):
        load_router_config(None, None)  # no replicas anywhere


def test_router_config_from_dict():
    cfg = RouterConfig.from_dict({
        "replicas": ["http://a:1", {"name": "bee", "url": "http://b:2"}],
        "policy": "random", "affinity_chunk": 32, "retries": 2})
    assert cfg.replicas == [("replica-0", "http://a:1"),
                            ("bee", "http://b:2")]
    assert cfg.policy == "random" and cfg.affinity_chunk == 32
    with pytest.raises(ValueError):
        RouterConfig(replicas=[("a", "http://a")], policy="round-robin")
    with pytest.raises(ValueError):
        RouterConfig(replicas=[])
    with pytest.raises(ValueError):
        RouterConfig.from_dict({"replicas": [{"name": "x"}]})  # no url


# ---- router app over fake replicas (real sockets) ---------------------------


class _Cluster:
    """N fake replicas + the router app, all in the test's event loop."""

    def __init__(self, n: int = 2, *, policy: str = "affinity",
                 ready_interval: float = 0.0, retries: int = 1, **cfg_kw):
        self.n = n
        self.policy = policy
        self.ready_interval = ready_interval
        self.retries = retries
        self.cfg_kw = cfg_kw
        self.states: list[FakeReplicaState] = []
        self.servers = []
        self.urls: list[str] = []

    async def __aenter__(self):
        from quorum_tpu.server.serve import start_server

        for i in range(self.n):
            st = FakeReplicaState(f"r{i}")
            srv = await start_server(
                create_fake_replica_app(st), "127.0.0.1", 0)
            self.states.append(st)
            self.servers.append(srv)
            self.urls.append(
                f"http://127.0.0.1:{srv.sockets[0].getsockname()[1]}")
        self.cfg = RouterConfig(
            replicas=[(f"r{i}", u) for i, u in enumerate(self.urls)],
            policy=self.policy, ready_interval=self.ready_interval,
            retries=self.retries, **self.cfg_kw)
        self.app = create_router_app(self.cfg)
        self.mgr = self.app.state["replica_set"]
        self.client = httpx.AsyncClient(
            transport=httpx.ASGITransport(app=self.app),
            base_url="http://router", timeout=30.0)
        return self

    async def __aexit__(self, *exc):
        await self.client.aclose()
        await self.mgr.aclose()
        for srv in self.servers:
            srv.close()

    async def chat(self, messages, **kw):
        return await self.client.post(
            "/chat/completions",
            json={"model": "m", "messages": messages, **kw})


def _conv(i: int) -> list[dict]:
    return [{"role": "user", "content": f"router test conversation {i}: "
             "what is the opening move?"}]


async def test_router_affinity_places_turns_together():
    async with _Cluster(2) as c:
        homes = {}
        for i in range(8):
            msgs = _conv(i)
            r = await c.chat(msgs)
            assert r.status_code == 200, r.text
            homes[i] = r.headers["x-routed-to"]
            for t in range(2):
                msgs = msgs + [
                    {"role": "assistant",
                     "content": r.json()["choices"][0]["message"]["content"]},
                    {"role": "user", "content": f"follow-up {t}"}]
                r = await c.chat(msgs)
                assert r.headers["x-routed-to"] == homes[i], (i, t)
        assert len(set(homes.values())) == 2  # both replicas used
        # replica-side truth: later turns hit the prefix store
        assert sum(s.prefix_hits for s in c.states) >= 8


async def test_router_streaming_passthrough():
    async with _Cluster(2) as c:
        async with c.client.stream(
            "POST", "/chat/completions",
            json={"model": "m", "stream": True, "messages": _conv(0)},
        ) as resp:
            assert resp.status_code == 200
            assert resp.headers["x-routed-to"].startswith("r")
            body = (await resp.aread()).decode()
        frames = [ln for ln in body.splitlines() if ln.startswith("data: ")]
        assert frames[-1] == "data: [DONE]"
        # upstream's role chunk leads; its finish chunk precedes [DONE]
        import json as _json

        events = [_json.loads(f[6:]) for f in frames[:-1]]
        assert events[0]["choices"][0]["delta"].get("role") == "assistant"
        assert events[-1]["choices"][0]["finish_reason"] == "stop"
        contents = [e["choices"][0]["delta"].get("content")
                    for e in events[1:-1]]
        assert all(contents)


async def test_router_failover_to_next_candidate():
    """A dead primary (connection refused) fails over pre-stream; the
    request completes on the survivor and the failover is counted."""
    from quorum_tpu.observability import ROUTER_FAILOVERS

    async with _Cluster(2) as c:
        # kill r0's listener; its port now refuses connections
        c.servers[0].close()
        await c.servers[0].wait_closed()
        ok = dead = 0
        for i in range(10):
            before = ROUTER_FAILOVERS.value_of(replica="r0")
            r = await c.chat(_conv(i))
            assert r.status_code == 200, r.text
            if r.headers["x-routed-to"] == "r1":
                ok += 1
            if ROUTER_FAILOVERS.value_of(replica="r0") > before:
                dead += 1
        assert ok == 10  # every request served by the survivor
        assert dead >= 1  # at least one went through the failover path
        # streaming fails over pre-first-byte too
        async with c.client.stream(
            "POST", "/chat/completions",
            json={"model": "m", "stream": True, "messages": _conv(99)},
        ) as resp:
            assert resp.status_code == 200
            assert resp.headers["x-routed-to"] == "r1"
            assert b"[DONE]" in await resp.aread()


async def test_router_breaker_opens_and_sheds_when_all_down():
    async with _Cluster(2, breaker_threshold=2,
                        breaker_cooldown=30.0) as c:
        for srv in c.servers:
            srv.close()
            await srv.wait_closed()
        # failure storm opens both breakers
        for i in range(4):
            r = await c.chat(_conv(i))
            assert r.status_code >= 500
        r = await c.chat(_conv(0))
        assert r.status_code == 503
        assert "retry-after" in {k.lower() for k in r.headers}
        health = (await c.client.get("/health"))
        assert health.status_code in (200, 503)


async def test_router_ready_rotation_and_migration_warmth():
    """A replica that sheds (/ready 503) rotates out; its prefix chains
    migrate to the survivor, which then serves the spilled conversation
    with a warm store hit."""
    async with _Cluster(2, ready_interval=0.0) as c:
        homes = {}
        for i in range(8):
            r = await c.chat(_conv(i))
            homes[i] = r.headers["x-routed-to"]
        shed = homes[[i for i in homes if homes[i] == "r0"][0]]
        assert shed == "r0"
        # admin-shed r0, then run one poll sweep by hand (interval 0 =
        # no background poller; tests drive sweeps deterministically)
        async with httpx.AsyncClient() as direct:
            await direct.post(f"{c.urls[0]}/admin/shed")
        await c.mgr.poll_once()
        assert "r0" not in c.mgr.ring and "r1" in c.mgr.ring
        assert c.mgr.n_migrations == 1
        surv = c.states[1]
        hits_before = surv.prefix_hits
        for i in homes:
            if homes[i] != "r0":
                continue
            r = await c.chat(_conv(i))
            assert r.headers["x-routed-to"] == "r1"
            assert int(r.headers["x-prefix-matched"]) > 0, i
        assert surv.prefix_hits > hits_before
        # recovery: replica rejoins on the next sweep and reclaims keys
        async with httpx.AsyncClient() as direct:
            await direct.post(f"{c.urls[0]}/admin/recover")
        await c.mgr.poll_once()
        assert "r0" in c.mgr.ring
        i0 = [i for i in homes if homes[i] == "r0"][0]
        r = await c.chat(_conv(i0))
        assert r.headers["x-routed-to"] == "r0"


async def test_router_streaming_inflight_never_leaks():
    """The in-flight counter must return to zero on EVERY stream ending:
    normal exhaustion, and an aclose() on a response generator whose body
    never ran (a client that disconnected before the response started) —
    the leak that would let bounded-load placement drift all traffic off
    a healthy replica."""
    async with _Cluster(2) as c:
        # normal streaming completion
        async with c.client.stream(
            "POST", "/chat/completions",
            json={"model": "m", "stream": True, "messages": _conv(1)},
        ) as resp:
            await resp.aread()
        assert all(r.inflight == 0 for r in c.mgr.replicas.values())
        # abandoned-before-start: drive the handler directly and close
        # the response iterator without ever iterating it (what the ASGI
        # server does when http.response.start fails on a gone client)
        from quorum_tpu.server.asgi import Request, StreamingResponse

        async def receive():
            import json as _json

            return {"type": "http.request",
                    "body": _json.dumps(
                        {"model": "m", "stream": True,
                         "messages": _conv(2)}).encode(),
                    "more_body": False}

        scope = {"type": "http", "method": "POST",
                 "path": "/chat/completions", "headers": []}
        handler = c.app._routes[("POST", "/chat/completions")]
        resp = await handler(Request(scope, receive))
        assert isinstance(resp, StreamingResponse)
        assert sum(r.inflight for r in c.mgr.replicas.values()) == 1
        await resp.iterator.aclose()  # body never iterated
        assert all(r.inflight == 0 for r in c.mgr.replicas.values())


async def test_router_random_policy_ignores_affinity():
    async with _Cluster(4, policy="random") as c:
        seen = set()
        for _ in range(12):
            r = await c.chat(_conv(0))  # SAME conversation every time
            seen.add(r.headers["x-routed-to"])
        assert len(seen) > 1  # affinity would pin all 12 to one replica


async def test_router_surfaces():
    async with _Cluster(2) as c:
        h = (await c.client.get("/health")).json()
        assert h["status"] == "healthy" and len(h["replicas"]) == 2
        assert (await c.client.get("/ready")).status_code == 200
        m = (await c.client.get("/metrics")).text
        from quorum_tpu.observability import validate_exposition

        assert validate_exposition(m) == [], validate_exposition(m)[:3]
        assert "quorum_tpu_router_replica_up" in m
        assert "quorum_tpu_router_requests_total" in m
        rr = (await c.client.get("/router/replicas")).json()
        assert rr["policy"] == "affinity" and len(rr["replicas"]) == 2
        # invalid JSON body → router's own 400, no replica involved
        bad = await c.client.post("/chat/completions", content=b"nope")
        assert bad.status_code == 400
        # unknown migrate source → 404
        r = await c.client.post("/router/migrate?from=nope")
        assert r.status_code == 404


async def test_router_admin_migrate_endpoint():
    async with _Cluster(2) as c:
        for i in range(8):
            await c.chat(_conv(i))
        src = "r0" if c.states[0].requests else "r1"
        dst = "r1" if src == "r0" else "r0"
        r = await c.client.post(f"/router/migrate?from={src}&to={dst}")
        assert r.status_code == 200
        out = r.json()
        assert out["migrated_chains"] >= 1 and out["migrated_bytes"] > 0
        assert out["targets"] == [dst]


# ---- mid-stream resume + graceful drain (zero-loss streams) -----------------


async def _collect(c: _Cluster, body: dict):
    """Stream ``body`` through the router; return (events, headers)."""
    import json as _json

    async with c.client.stream(
            "POST", "/chat/completions", json=body) as resp:
        assert resp.status_code == 200, await resp.aread()
        headers = dict(resp.headers)
        raw = (await resp.aread()).decode()
    frames = [ln[6:] for ln in raw.splitlines() if ln.startswith("data: ")]
    assert frames and frames[-1] == "[DONE]"
    return [_json.loads(f) for f in frames[:-1]], headers


def _content(events: list[dict]) -> str:
    return "".join((c.get("delta") or {}).get("content") or ""
                   for e in events for c in e.get("choices") or [])


async def test_router_stream_resume_token_exact():
    """A mid-stream death resumes on the sibling with the client-visible
    sequence identical to an uninterrupted run: one role chunk, one chunk
    identity, no error chunks, no duplicate or dropped content — and the
    resume is counted."""
    from quorum_tpu.observability import ROUTER_STREAM_RESUMES

    async with _Cluster(2) as c:
        body = {"model": "m", "stream": True, "messages": _conv(0)}
        base_events, base_h = await _collect(c, body)
        base_text = _content(base_events)
        assert base_text
        # arm a one-shot mid-stream death on the serving replica
        home = int(base_h["x-routed-to"][1:])
        c.states[home].abort_after = 2
        before = ROUTER_STREAM_RESUMES.value_of(outcome="resumed")
        events, _ = await _collect(c, body)
        assert _content(events) == base_text
        assert not any(e.get("id") == "error" for e in events)
        roles = [e for e in events if e.get("choices")
                 and (e["choices"][0].get("delta") or {}).get("role")]
        assert len(roles) == 1  # the replacement's role chunk is swallowed
        assert len({e["id"] for e in events if e.get("id")}) == 1
        assert events[-1]["choices"][0]["finish_reason"] == "stop"
        # qt_tokens is router-internal metadata — never reaches the client
        assert not any("qt_tokens" in e for e in events)
        assert ROUTER_STREAM_RESUMES.value_of(outcome="resumed") \
            == before + 1


async def test_router_stream_resume_usage_union():
    """Usage across a resume splice is the union: ``completion_tokens``
    counts each generated token ONCE (journal size), never journal +
    replayed continuation."""
    async with _Cluster(2) as c:
        body = {"model": "m", "stream": True, "messages": _conv(3),
                "stream_options": {"include_usage": True}}
        base_events, base_h = await _collect(c, body)
        base_usage = [e["usage"] for e in base_events if e.get("usage")]
        assert len(base_usage) == 1
        c.states[int(base_h["x-routed-to"][1:])].abort_after = 2
        events, _ = await _collect(c, body)
        usage = [e["usage"] for e in events if e.get("usage")]
        assert len(usage) == 1
        assert usage[0] == base_usage[0]  # identical to the unbroken run


async def test_router_stream_resume_divergence_degrades():
    """When the survivor's replay guard refuses the journal, the stream
    degrades to the error-chunk contract: delivered content stays a clean
    prefix (no duplicate frames), exactly one error chunk, then [DONE].
    Classification rides the structured ``qt_error`` marker, which —
    like ``qt_tokens`` — never reaches the client."""
    from quorum_tpu.observability import ROUTER_STREAM_RESUMES

    async with _Cluster(2) as c:
        body = {"model": "m", "stream": True, "messages": _conv(5)}
        base_events, base_h = await _collect(c, body)
        base_text = _content(base_events)
        for st in c.states:
            st.diverge_resume = True
        c.states[int(base_h["x-routed-to"][1:])].abort_after = 2
        before = ROUTER_STREAM_RESUMES.value_of(outcome="divergence")
        events, _ = await _collect(c, body)
        errors = [e for e in events if e.get("id") == "error"]
        assert len(errors) == 1
        assert "diverged" in errors[0]["choices"][0]["delta"]["content"]
        assert errors[0]["choices"][0]["finish_reason"] == "error"
        assert not any("qt_error" in e for e in events)
        text = _content(events[:-1])
        assert base_text.startswith(text) and text != base_text
        assert ROUTER_STREAM_RESUMES.value_of(outcome="divergence") \
            == before + 1


async def test_router_client_token_ids_passthrough_disables_resume():
    """A client that itself asks for ``stream_token_ids`` gets the ids
    untouched — and the router cannot journal that stream (the knob is
    the client's), so a death degrades to the error-chunk contract."""
    async with _Cluster(2) as c:
        body = {"model": "m", "stream": True, "messages": _conv(7),
                "stream_token_ids": True}
        events, h = await _collect(c, body)
        content = [e for e in events
                   if _content([e])]
        assert content and all(e.get("qt_tokens") for e in content)
        c.states[int(h["x-routed-to"][1:])].abort_after = 1
        events2, _ = await _collect(c, body)
        errors = [e for e in events2 if e.get("id") == "error"]
        assert len(errors) == 1


async def test_router_stream_resume_disabled_keeps_error_contract():
    """``stream_resume: false`` restores the PR 12 behavior byte-for-byte:
    one error chunk, [DONE], no second submission."""
    async with _Cluster(2, stream_resume=False) as c:
        body = {"model": "m", "stream": True, "messages": _conv(9)}
        _, h = await _collect(c, body)
        home = int(h["x-routed-to"][1:])
        requests_before = [st.requests for st in c.states]
        c.states[home].abort_after = 1
        events, _ = await _collect(c, body)
        errors = [e for e in events if e.get("id") == "error"]
        assert len(errors) == 1
        after = [st.requests for st in c.states]
        assert sum(after) == sum(requests_before) + 1  # no re-placement


async def test_router_park_without_journal_degrades_to_error_chunk():
    """A drain park on a stream the router cannot resume (``stream_resume``
    off → no journal) must not relay the internal ``parked`` finish to
    the client: it degrades to the error-chunk contract — one error
    chunk, then [DONE]."""
    async with _Cluster(2, stream_resume=False) as c:
        for st in c.states:
            st.park_streams = True
        body = {"model": "m", "stream": True, "messages": _conv(11)}
        events, _ = await _collect(c, body)
        finishes = [ch.get("finish_reason")
                    for e in events for ch in e.get("choices") or []]
        assert "parked" not in finishes
        errors = [e for e in events if e.get("id") == "error"]
        assert len(errors) == 1
        assert "parked" in errors[0]["choices"][0]["delta"]["content"]


async def test_router_drain_zero_loss():
    """POST /router/drain gracefully empties one replica under live
    traffic: the in-flight stream parks, resumes on the sibling, and the
    client sees the identical uninterrupted token sequence — zero failed
    requests; the drained replica leaves the ring and new turns route to
    the survivor with migrated-prefix warmth."""
    async with _Cluster(2) as c:
        body = {"model": "m", "stream": True, "messages": _conv(2)}
        base_events, base_h = await _collect(c, body)
        base_text = _content(base_events)
        home = base_h["x-routed-to"]
        # slow the scripted decode so the drain lands mid-stream
        for st in c.states:
            st.chunk_delay = 0.02
        task = asyncio.ensure_future(_collect(c, body))
        await asyncio.sleep(0.05)  # a few chunks in
        r = await c.client.post(f"/router/drain?replica={home}")
        assert r.status_code == 200, r.text
        report = r.json()
        assert report["drained"] is True and report["resident"] == 0
        events, _ = await task
        assert _content(events) == base_text
        assert not any(e.get("id") == "error" for e in events)
        assert c.states[int(home[1:])].n_parked == 1
        # membership: out of the ring, new turns go to the survivor
        assert home not in c.mgr.ring
        r2 = await c.chat(_conv(2))
        assert r2.status_code == 200
        assert r2.headers["x-routed-to"] != home
        # unknown replica → 404
        assert (await c.client.post(
            "/router/drain?replica=nope")).status_code == 404


async def test_router_resume_fault_site_falls_to_next_candidate():
    """An injected failure at ``router.resume`` burns the first candidate
    and the resume commits on the next one (N=3 so a sibling remains)."""
    from quorum_tpu import faults
    from quorum_tpu.observability import ROUTER_STREAM_RESUMES

    async with _Cluster(3) as c:
        body = {"model": "m", "stream": True, "messages": _conv(11)}
        base_events, base_h = await _collect(c, body)
        base_text = _content(base_events)
        c.states[int(base_h["x-routed-to"][1:])].abort_after = 1
        failed = ROUTER_STREAM_RESUMES.value_of(outcome="failed")
        resumed = ROUTER_STREAM_RESUMES.value_of(outcome="resumed")
        faults.arm("router.resume", times=1)
        try:
            events, _ = await _collect(c, body)
        finally:
            faults.disarm()
        assert _content(events) == base_text
        assert not any(e.get("id") == "error" for e in events)
        assert ROUTER_STREAM_RESUMES.value_of(outcome="failed") \
            == failed + 1
        assert ROUTER_STREAM_RESUMES.value_of(outcome="resumed") \
            == resumed + 1


# ---- real-engine migration round trip (slow tier) ---------------------------


@slow
async def test_prefix_migration_round_trip_between_engines():
    """The acceptance gate: a chunk chain exported from engine A and
    seeded into engine B produces a tier-hit restore on B, with outputs
    token-for-token identical to B's cold prefill of the same prompt."""
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models import resolve_spec
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = resolve_spec("llama-tiny", {"max_seq": "128"})
    greedy = SamplerConfig(temperature=0.0)
    chunk = 16

    def mk():
        return InferenceEngine(spec, decode_chunk=4, prefill_chunk=chunk,
                               n_slots=1, prefix_store="host",
                               prefix_store_chunk=chunk)

    prompt = [(3 + i * 7) % (spec.vocab_size - 1) + 1 for i in range(24)]
    eng_a, eng_b = mk(), mk()
    ref = InferenceEngine(spec, decode_chunk=4, prefill_chunk=chunk,
                          n_slots=1)
    try:
        gen1 = eng_a.generate(prompt, max_new_tokens=6, sampler=greedy,
                              seed=1).token_ids
        eng_a.drain_prefix_store()
        blob = eng_a.export_prefix_chunks()
        stats = prefix_wire.stats(blob)
        assert stats["chains"] >= 1 and stats["chunk_tokens"] == chunk

        got = eng_b.import_prefix_chunks(blob)
        assert got["tokens_imported"] >= chunk, got
        # churn B's only slot so the store — not tier-0 slot reuse —
        # must serve the restore
        eng_b.generate([9] * 30, max_new_tokens=4, sampler=greedy, seed=9)
        turn2 = prompt + gen1 + [77, 78, 79, 80, 81]
        got_b = eng_b.generate(turn2, max_new_tokens=6, sampler=greedy,
                               seed=2).token_ids
        assert eng_b.prefix_store_hits == 1  # the migrated chain HIT
        cold = ref.generate(turn2, max_new_tokens=6, sampler=greedy,
                            seed=2).token_ids
        assert got_b == cold, "migrated restore changed the generation"

        # a wrong-layout blob is rejected, never silently seeded
        other = InferenceEngine(spec, decode_chunk=4, prefill_chunk=chunk,
                                n_slots=1, prefix_store="host",
                                prefix_store_chunk=2 * chunk)
        try:
            with pytest.raises(ValueError):
                other.import_prefix_chunks(blob)
        finally:
            other.shutdown()
    finally:
        eng_a.shutdown()
        eng_b.shutdown()
        ref.shutdown()


@slow
async def test_prefix_chunk_http_routes():
    """GET export → store clear → PUT import over the live server routes:
    the wire survives the HTTP hop and the re-seeded store serves."""
    from quorum_tpu.config import Config
    from quorum_tpu.server.app import create_app

    config = {
        "settings": {"timeout": 60},
        "primary_backends": [
            {"name": "T",
             "url": "tpu://llama-tiny?seed=3&slots=1&prefill_chunk=16"
                    "&prefix_store=host&prefix_store_chunk=16"
                    "&max_seq=128&max_tokens=8",
             "model": "t"}],
    }
    auth = {"Authorization": "Bearer x"}
    long_msg = "a conversation opener long enough to fill chunks " * 3
    app = create_app(Config(raw=config), watch_config=False)
    backend = app.state["registry"].get("T")
    async with httpx.AsyncClient(
            transport=httpx.ASGITransport(app=app),
            base_url="http://testserver") as client:
        r = await client.post(
            "/chat/completions",
            json={"model": "t", "max_tokens": 6,
                  "messages": [{"role": "user", "content": long_msg}]},
            headers=auth)
        assert r.status_code == 200
        backend.engine.drain_prefix_store()
        resp = await client.get("/debug/prefix/chunks")
        assert resp.status_code == 200
        assert resp.headers["content-type"] == "application/octet-stream"
        assert resp.headers["x-prefix-chunk-tokens"] == "16"
        blob = resp.content
        assert prefix_wire.stats(blob)["chains"] >= 1

        backend.engine.prefix_store.clear()
        put = await client.put("/debug/prefix/chunks", content=blob)
        assert put.status_code == 200, put.text
        body = put.json()
        assert body["tokens_imported"] >= 16 and body["backend"] == "T"

        bad = await client.put("/debug/prefix/chunks", content=b"garbage")
        assert bad.status_code == 400
        assert bad.json()["error"]["type"] == "invalid_request_error"

        # ?max_bytes must bound or 400 — never a silent unbounded export
        ok = await client.get("/debug/prefix/chunks?max_bytes=999999999")
        assert ok.status_code == 200
        for bad_val in ("0", "-5", "10MB", "1e6"):
            r = await client.get(
                f"/debug/prefix/chunks?max_bytes={bad_val}")
            assert r.status_code == 400, bad_val


async def test_prefix_chunk_routes_404_without_store():
    from tests.conftest import make_client
    from quorum_tpu.backends.fake import FakeBackend

    config = {"settings": {"timeout": 5},
              "primary_backends": [
                  {"name": "F", "url": "http://f.example/v1",
                   "model": "f"}]}
    async with make_client(config, F=FakeBackend("F", text="x")) as client:
        r = await client.get("/debug/prefix/chunks")
        assert r.status_code == 404
        r = await client.put("/debug/prefix/chunks", content=b"zz")
        assert r.status_code == 404
