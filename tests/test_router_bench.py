"""Fast smoke over scripts/router_bench.py's fake leg — the `make verify`
wiring of the router-bench acceptance: 2 scripted replicas behind the real
router, affinity's prefix-hit rate strictly above the random baseline, and
per-conversation outputs token-for-token identical to single-replica
serving. The full bench (`make router-bench`) adds N=4 and the real
tiny-engine leg; this smoke runs the same entry point at toy scale."""

import importlib.util
import os
import sys


def _load_bench():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "router_bench.py")
    spec = importlib.util.spec_from_file_location("router_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_router_bench_fake_smoke():
    rb = _load_bench()
    out = rb.run_fake(2, n_conversations=6, turns=3, max_tokens=6)
    assert out["affinity_gt_random"], (
        out["affinity"]["hit_rate"], out["random"]["hit_rate"])
    assert out["affinity"]["outputs_pinned_vs_single"]
    assert out["random"]["outputs_pinned_vs_single"]
    assert out["affinity"]["completion_tokens"] > 0
    assert sum(out["affinity"]["requests_per_replica"]) == 6 * 3


def test_router_bench_resume_fake_smoke():
    """The zero-loss resume leg at toy scale (ISSUE 19): a scripted
    mid-stream death resumes on the sibling with the client-visible
    sequence identical to the uninterrupted run, and the leg reports the
    resume gap + replayed-journal size."""
    rb = _load_bench()
    out = rb.run_resume_fake(max_tokens=24)
    assert out["token_exact"], out
    assert out["resumed"] == 1, out
    assert out["replayed_tokens"] and out["replayed_tokens"] > 0, out
    assert out["resume_latency_s"] is not None \
        and out["resume_latency_s"] >= 0, out


def test_router_bench_quorum_fake_smoke():
    """The cross-cell quorum leg at toy scale (docs/quorum.md): quorum=3
    combine is full with the combined body pinned to 3x the deterministic
    single-member answer, a member kill with a spare in the ring finishes
    full (token-exact resume elsewhere), and killing the spare too serves
    the request degraded from the survivors — 200, 2/3 members, counter
    ticked (the 1.5x TTFT ratio is the bench's printed acceptance gate;
    wall-clock on a shared CI core flakes)."""
    rb = _load_bench()
    out = rb.run_quorum_fake(iters=4, max_tokens=8)
    assert out["combine_status"] == 200, out
    assert out["combine_outcome"] == "full", out
    assert out["combine_served"] == 3, out
    assert out["combined_pinned"], out
    assert out["single_ttft_p50_s"] > 0.0 and out["quorum_ttft_p50_s"] > 0.0
    assert out["kill_with_spare_outcome"] == "full", out
    assert out["degraded_status"] == 200, out
    assert out["degraded_served"] == 2, out
    assert out["degraded_reason"] == "member_failed", out
    assert out["degraded_counted"], out
