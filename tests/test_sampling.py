"""Direct unit tests of ops/sampling.py (previously pinned only through
engine-level equality tests): greedy reduction, top-k/top-p truncation,
row independence, and single-vs-batched consistency.
"""

import numpy as np

import jax
import jax.numpy as jnp

from quorum_tpu.ops.sampling import SamplerConfig, sample_token, sample_token_rows

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def _logits(seed, shape=(4, 64)):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)


def test_greedy_is_argmax_any_knobs():
    lg = _logits(0)
    key = jax.random.PRNGKey(1)
    out = sample_token(lg, key, SamplerConfig(temperature=0.0, top_p=0.3,
                                              top_k=5))
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(jnp.argmax(lg, -1)))


def test_top_k_one_and_tiny_top_p_reduce_to_argmax():
    lg = _logits(2)
    key = jax.random.PRNGKey(3)
    am = np.asarray(jnp.argmax(lg, -1))
    for cfg in (SamplerConfig(temperature=1.0, top_k=1),
                SamplerConfig(temperature=1.0, top_p=1e-6)):
        np.testing.assert_array_equal(
            np.asarray(sample_token(lg, key, cfg)), am)


def test_top_k_never_samples_outside_k():
    lg = _logits(4, (2, 32))
    k = 4
    topk_sets = [set(np.asarray(jax.lax.top_k(lg, k)[1])[r]) for r in (0, 1)]
    for seed in range(40):
        out = np.asarray(sample_token(lg, jax.random.PRNGKey(seed),
                                      SamplerConfig(temperature=1.5, top_k=k)))
        for r in (0, 1):
            assert out[r] in topk_sets[r]


def test_rows_match_single_and_are_independent():
    lg = _logits(5, (3, 64))
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(10, 13))
    temp = jnp.array([0.0, 0.8, 1.2])
    topp = jnp.array([1.0, 0.9, 1.0])
    topk = jnp.array([0, 0, 8], jnp.int32)
    out = np.asarray(sample_token_rows(lg, keys, temp, topp, topk))
    # row 0 greedy
    assert out[0] == int(jnp.argmax(lg[0]))
    # row independence: mutating OTHER rows' logits/knobs leaves a row alone
    lg2 = lg.at[0].set(-lg[0])
    out2 = np.asarray(sample_token_rows(
        lg2, keys, jnp.array([1.0, 0.8, 1.2]), topp, topk))
    assert out2[1] == out[1] and out2[2] == out[2]
    # batched row matches the single-stream sampler given the same key/knobs
    one = sample_token(lg[2][None], keys[2],
                       SamplerConfig(temperature=1.2, top_k=8))
    assert out[2] == int(one[0])
