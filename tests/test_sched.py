"""QoS scheduler (``qos=1``, ISSUE 18, docs/scheduling.md) acceptance:

- **policy core**: explicit ``priority`` wins, headroom derives the rest
  (``background`` never derived); WFQ gives every backlogged class its
  w/Σw share; within a class earliest-deadline-headroom first, with
  resume credit ahead of fresh arrivals; idle classes cannot bank credit.
- **cost model**: the ONE shed decision point — capacity messages stay
  byte-identical to the pre-QoS engine, Retry-After turns honest once the
  EWMAs are warm, and the predictive shed never fires cold or with QoS
  off.
- **preemption**: an interactive arrival with no free slot parks a
  strictly-lower-class resident at a reap boundary, and the parked stream
  is TOKEN-FOR-TOKEN identical to its unpreempted run — greedy and
  sampled, dense and paged, colocated and zero-drain (the replay-based
  resume contract; no new device programs).
- **cache-key pin**: ``qos`` is not part of the engine cache key — a
  qos=1 backend shares the qos=0 backend's engine and flips the flag
  (opt-in wins, the prefix_cache sharing rule).
- **knob validation**: malformed ``priority``/``tenant`` are one 400 at
  the HTTP edge and a ValueError at ``engine.submit``.

Pure host policy/cost/controller tests are fast-tier; engine-scale
preemption drills are slow-tier like every other engine test."""

import dataclasses
import threading
import time

import pytest

from quorum_tpu import oai
from quorum_tpu.engine.engine import (
    DeadlineExceeded,
    EngineBreakerOpen,
    InferenceEngine,
    QueueFullError,
    get_engine,
)
from quorum_tpu.models.model_config import MODEL_PRESETS
from quorum_tpu.ops.sampling import SamplerConfig
from quorum_tpu.sched import (
    PRIORITY_CLASSES,
    CostModel,
    PreemptionController,
    SchedPolicy,
    class_rank,
    to_slo_class,
)
from quorum_tpu.sched.cost import MARGIN, MIN_OBS
from quorum_tpu.sched.policy import _env_weights

slow = pytest.mark.slow

SPEC = dataclasses.replace(MODEL_PRESETS["llama-tiny"], max_seq=128)
GREEDY = SamplerConfig(temperature=0.0)
SAMPLED = SamplerConfig(temperature=0.9, top_p=0.9)


class FakeReq:
    """The duck-typed subset of engine._Request the policy layer reads."""

    def __init__(self, cls="batch", deadline=None, t_submit=0.0,
                 n_preempts=0, tenant=None, cancelled=False, want_lp=-1,
                 emitted=0, preempt_flag=False, rid="r"):
        self.sched_class = cls
        self.deadline = deadline
        self.t_submit = t_submit
        self.n_preempts = n_preempts
        self.tenant = tenant
        self.cancel = threading.Event()
        if cancelled:
            self.cancel.set()
        self.want_lp = want_lp
        self.emitted = emitted
        self.preempt_flag = preempt_flag
        self.rid = rid


# ---- policy core (fast) ----------------------------------------------------


def test_classify_explicit_knob_wins_background_never_derived():
    p = SchedPolicy()
    now = 100.0
    assert p.classify("background", now + 1, now) == "background"
    assert p.classify("interactive", None, now) == "interactive"
    # derived: tight headroom -> interactive, loose/none -> batch
    assert p.classify(None, now + 1.0, now) == "interactive"
    assert p.classify(None, now + 10_000.0, now) == "batch"
    assert p.classify(None, None, now) == "batch"
    # background is NEVER derived, only explicit
    for deadline in (None, now + 0.5, now + 10_000.0):
        assert p.classify(None, deadline, now) != "background"


def test_class_rank_and_slo_mapping():
    assert class_rank("interactive") < class_rank("batch") \
        < class_rank("background")
    assert class_rank("no-such-class") == class_rank("batch")
    assert to_slo_class("interactive") == "interactive"
    assert to_slo_class("batch") == "batch"
    assert to_slo_class("background") == "batch"


def test_wfq_backlogged_share_meets_floor():
    """With every class backlogged, an order() pass interleaves classes
    by weight: each class receives at least ~w/Σw of any admission
    window (the starvation bound)."""
    p = SchedPolicy(weights={"interactive": 4, "batch": 2, "background": 1})
    pending = ([FakeReq("interactive", t_submit=i) for i in range(14)]
               + [FakeReq("batch", t_submit=i) for i in range(14)]
               + [FakeReq("background", t_submit=i) for i in range(14)])
    ordered = p.order(pending, now=0.0)
    first14 = [r.sched_class for r in ordered[:14]]
    # 14 admissions at 4:2:1 -> interactive 8, batch 4, background 2
    assert first14.count("interactive") == 8, first14
    assert first14.count("batch") == 4, first14
    assert first14.count("background") == 2, first14
    # order() simulated picks must not move the real clocks
    assert all(v == 0.0 for v in p._vtime.values())


def test_within_class_headroom_then_fifo_then_resume_credit():
    p = SchedPolicy()
    now = 50.0
    tight = FakeReq("batch", deadline=now + 1, t_submit=3.0)
    loose = FakeReq("batch", deadline=now + 100, t_submit=1.0)
    none_ = FakeReq("batch", deadline=None, t_submit=0.0)
    pending = [none_, loose, tight]
    assert pending[p.pick(pending, now)] is tight  # earliest headroom
    # resume credit beats even tighter headroom: a parked victim goes
    # first within its class
    parked = FakeReq("batch", deadline=None, t_submit=9.0, n_preempts=1)
    pending = [none_, loose, tight, parked]
    assert pending[p.pick(pending, now)] is parked
    # FIFO is the final tie-break
    a = FakeReq("batch", t_submit=1.0)
    b = FakeReq("batch", t_submit=2.0)
    assert [b, a][p.pick([b, a], now)] is a


def test_idle_class_cannot_bank_credit():
    """A long-idle class's clock re-syncs to the floor on its next
    charge: it does not monopolize admissions afterwards."""
    p = SchedPolicy(weights={"interactive": 1, "batch": 1, "background": 1})
    for i in range(50):  # batch runs alone for a long while
        p.charge(FakeReq("batch"))
    pending = [FakeReq("interactive", t_submit=i) for i in range(4)] \
        + [FakeReq("batch", t_submit=i) for i in range(4)]
    ordered = p.order(pending, now=0.0)
    # equal weights -> the first charge clamps interactive's clock to the
    # system floor, so it gets at most ~one turn of credit: batch is back
    # in rotation within three picks instead of after four
    classes = [r.sched_class for r in ordered]
    assert "batch" in classes[:3], classes
    assert classes[:4] != ["interactive"] * 4, classes


def test_tenant_weight_scales_within_class():
    p = SchedPolicy(weights={"interactive": 1, "batch": 1, "background": 1},
                    tenant_weights={"heavy": 4.0})
    # identical classes: the heavy tenant's admissions advance the class
    # clock 4x slower, so its requests cost less virtual time
    before = dict(p._vtime)
    p.charge(FakeReq("batch", tenant="heavy"))
    light_cost = None
    q = SchedPolicy(weights={"interactive": 1, "batch": 1, "background": 1},
                    tenant_weights={"heavy": 4.0})
    q.charge(FakeReq("batch", tenant=None))
    light_cost = q._vtime["batch"]
    assert p._vtime["batch"] == pytest.approx(light_cost / 4.0)
    assert before["batch"] == 0.0


def test_env_weights_parse_and_clamp(monkeypatch):
    monkeypatch.setenv("X_W", "batch=5,junk,=3,background=-1,"
                              "interactive=not-a-number, spaced = 2 ")
    w = _env_weights("X_W", {"interactive": 4.0, "batch": 2.0,
                             "background": 1.0})
    assert w["batch"] == 5.0          # parsed
    assert w["background"] == 1.0     # non-positive ignored
    assert w["interactive"] == 4.0    # malformed ignored
    assert w["spaced"] == 2.0         # whitespace tolerated


def test_queue_depths_counts_by_class():
    p = SchedPolicy()
    pending = [FakeReq("interactive"), FakeReq("batch"), FakeReq("batch"),
               FakeReq("background")]
    assert p.queue_depths(pending) == {
        "interactive": 1, "batch": 2, "background": 1}
    assert p.queue_depths([]) == {c: 0 for c in PRIORITY_CLASSES}


# ---- cost model (fast) -----------------------------------------------------


class FakeBreaker:
    def __init__(self, open_=False, ra=5.0):
        self._open = open_
        self._ra = ra

    def allow(self, now):
        return not self._open

    def retry_after(self, now):
        return self._ra


def test_presubmit_deadline_and_breaker():
    cm = CostModel()
    assert cm.presubmit(now=10.0, deadline=20.0, breaker=None) is None
    d = cm.presubmit(now=10.0, deadline=10.0, breaker=None)
    assert d is not None and d.kind == "deadline"
    b = cm.presubmit(now=10.0, deadline=None, breaker=FakeBreaker(True, 7.5))
    assert b is not None and b.kind == "breaker" and b.retry_after == 7.5


def test_capacity_messages_byte_identical_to_pre_qos():
    """The queue-full and pool-span texts are a client-facing contract
    (the HTTP layer threads them into 503 bodies verbatim)."""
    cm = CostModel()
    full = cm.queue_check(now=0.0, deadline=None, n_pending=8,
                          max_pending=8, qos=False)
    assert full.kind == "queue_full"
    assert full.detail == "engine admission queue full (8 waiting)"
    assert full.retry_after == 1.0  # cold: the historical floor
    span = cm.queue_check(now=0.0, deadline=None, n_pending=0,
                          max_pending=8, qos=False, page_need=40,
                          pool_pages=32)
    assert span.kind == "pool_span"
    assert span.detail == ("request span of 40 pages exceeds the kv page "
                           "pool (32 pages)")


def test_predictive_shed_gates_cold_warm_and_off():
    cm = CostModel()
    tight = dict(now=0.0, deadline=0.5, n_pending=4, max_pending=64)
    # cold: no evidence, never sheds (FIFO-era behaviour preserved)
    assert cm.queue_check(qos=True, **tight) is None
    for _ in range(MIN_OBS):
        cm.observe_queue_wait(2.0)
        cm.observe_service(3.0)
    # warm + qos: est = 2 + 3*3 = 11s >> MARGIN * 0.5s -> shed
    d = cm.queue_check(qos=True, **tight)
    assert d is not None and d.kind == "deadline"
    assert d.retry_after >= 1.0
    assert cm.n_predictive_sheds == 1
    # same evidence, qos off: never predictive-sheds
    assert cm.queue_check(qos=False, **tight) is None
    # no deadline: nothing to be infeasible against
    assert cm.queue_check(qos=True, now=0.0, deadline=None, n_pending=4,
                          max_pending=64) is None
    # empty queue: the head admits immediately, no prediction
    assert cm.queue_check(qos=True, now=0.0, deadline=0.5, n_pending=0,
                          max_pending=64) is None
    # generous headroom: est within MARGIN x remaining -> no shed
    assert cm.queue_check(qos=True, now=0.0, deadline=100.0, n_pending=4,
                          max_pending=64) is None


def test_retry_hint_honest_once_warm():
    cm = CostModel()
    assert cm.retry_hint() == 1.0
    for _ in range(MIN_OBS):
        cm.observe_queue_wait(4.0)
    assert cm.retry_hint() == pytest.approx(4.0)
    # sub-second queues keep the 1s floor the HTTP layer always advertised
    cm2 = CostModel()
    for _ in range(MIN_OBS):
        cm2.observe_queue_wait(0.05)
    assert cm2.retry_hint() == 1.0


def test_estimated_queue_wait_shape():
    cm = CostModel()
    assert cm.estimated_queue_wait(3) is None  # cold
    for _ in range(MIN_OBS):
        cm.observe_queue_wait(1.0)
        cm.observe_service(2.0)
    assert cm.estimated_queue_wait(1) == pytest.approx(1.0)
    assert cm.estimated_queue_wait(3) == pytest.approx(1.0 + 2 * 2.0)


def test_expired_predicate_and_snapshot():
    cm = CostModel()
    r = FakeReq(deadline=5.0)
    assert CostModel.expired(r, 6.0)
    assert not CostModel.expired(r, 4.0)
    r.cancel.set()
    assert not CostModel.expired(r, 6.0)  # already cancelled: not re-shed
    assert not CostModel.expired(FakeReq(deadline=None), 6.0)
    snap = cm.snapshot()
    assert set(snap) == {"queue_wait_ewma_s", "service_ewma_s",
                         "queue_obs", "service_obs", "predictive_sheds"}
    # MARGIN is the documented 2x conservatism; a drive-by change to it
    # should have to touch this pin
    assert MARGIN == 2.0


# ---- preemption controller (fast) ------------------------------------------


def test_pick_victim_strictly_lower_class_only():
    pc = PreemptionController()
    head = FakeReq("interactive")
    slots = [FakeReq("interactive"), FakeReq("batch", emitted=5)]
    row, victim = pc.pick_victim(head, slots, 0, len(slots))
    assert row == 1 and victim is slots[1]
    # equal class is never a victim
    assert pc.pick_victim(FakeReq("batch"), [FakeReq("batch")], 0, 1) is None
    # and a batch head can still preempt background
    row, victim = pc.pick_victim(
        FakeReq("batch"), [FakeReq("background", emitted=1)], 0, 1)
    assert victim.sched_class == "background"


def test_pick_victim_order_lowest_class_fewest_tokens_youngest():
    pc = PreemptionController()
    head = FakeReq("interactive")
    bg_cheap = FakeReq("background", emitted=2, t_submit=5.0)
    bg_deep = FakeReq("background", emitted=40, t_submit=1.0)
    batch = FakeReq("batch", emitted=0, t_submit=0.0)
    slots = [batch, bg_deep, bg_cheap]
    row, victim = pc.pick_victim(head, slots, 0, len(slots))
    assert victim is bg_cheap  # lowest class first, then fewest tokens


def test_pick_victim_exclusions():
    pc = PreemptionController(max_preempts=2)
    head = FakeReq("interactive")
    for bad in (FakeReq("batch", cancelled=True),
                FakeReq("batch", preempt_flag=True),
                FakeReq("batch", want_lp=0),       # logprobs delivered
                FakeReq("batch", n_preempts=2),    # budget exhausted
                None):
        assert pc.pick_victim(head, [bad], 0, 1) is None


# ---- knob validation (fast) ------------------------------------------------


def test_http_priority_and_tenant_validation():
    ok = {"messages": [{"role": "user", "content": "hi"}]}
    assert oai.validate_request_body({**ok, "priority": "interactive"}) \
        is None
    assert oai.validate_request_body({**ok, "tenant": "acme"}) is None
    for bad in ("urgent", 3, True, ""):
        err = oai.validate_request_body({**ok, "priority": bad})
        assert err is not None and "priority" in err, bad
    for bad in ("", "x" * 65, 7, ["t"]):
        err = oai.validate_request_body({**ok, "tenant": bad})
        assert err is not None and "tenant" in err, bad


# ---- engine integration (slow) ---------------------------------------------


def _drain(eng, req, sink):
    for t in eng.stream_results(req):
        sink.append(t)


def _preempt_drill(eng, sampler, *, seed=5):
    """Run the canonical park/resume drill on ``eng`` (qos=1, slots=1):
    a batch stream is mid-decode when an interactive arrival lands; the
    victim must resume and match its solo run token for token."""
    victim_ids = [11, 13, 17, 19, 23, 29]
    solo = list(eng.stream_results(eng.submit(
        list(victim_ids), max_new_tokens=40, sampler=sampler, seed=seed)))
    assert len(solo) == 40
    before = eng.n_preemptions
    victim = eng.submit(list(victim_ids), max_new_tokens=40,
                        sampler=sampler, seed=seed, priority="batch")
    got: list = []
    th = threading.Thread(target=_drain, args=(eng, victim, got),
                          daemon=True)
    th.start()
    deadline = time.time() + 60
    while victim.emitted < 8 and time.time() < deadline:
        time.sleep(0.005)
    assert victim.emitted >= 8, "victim never reached mid-decode"
    bene = eng.submit([41, 43, 47], max_new_tokens=6, sampler=sampler,
                      seed=9, priority="interactive")
    bene_got = list(eng.stream_results(bene))
    th.join(120)
    assert not th.is_alive(), "victim stream never completed"
    assert len(bene_got) == 6
    assert eng.n_preemptions == before + 1, \
        f"preemptions {before}->{eng.n_preemptions}"
    assert got == solo, (len(got), len(solo))


@slow
@pytest.mark.parametrize("kw", [
    dict(),                                   # dense colocated
    dict(kv_pages=True, kv_page_size=16),     # paged colocated
    dict(zero_drain=True, prefill_chunk=16),  # dense zero-drain
], ids=["dense", "paged", "zero_drain"])
@pytest.mark.parametrize("sampler", [GREEDY, SAMPLED],
                         ids=["greedy", "sampled"])
def test_preempted_stream_token_exact(kw, sampler):
    eng = InferenceEngine(SPEC, seed=0, n_slots=1, decode_chunk=4,
                          qos=True, **kw)
    try:
        _preempt_drill(eng, sampler)
        m = eng.metrics()
        assert m["qos"] == 1
        assert m["preemptions_total"] >= 1
        assert m["preempted_tokens_total"] >= 8
        assert m["replayed_tokens_total"] == m["preempted_tokens_total"]
        if eng.kv_pages:
            # exact page accounting across park/resume: nothing leaked
            # (allocated = retained prefix donors only, zero live claims)
            assert m["kv_pages_allocated"] + m["kv_pages_free"] == \
                eng.kv_pool_pages
            with eng._cond:
                assert all(c == 0 for c in eng._page_claims)
    finally:
        eng.shutdown()


@slow
@pytest.mark.parametrize("sampler", [GREEDY, SAMPLED],
                         ids=["greedy", "sampled"])
def test_members_preemption_token_exact_and_member_local(sampler):
    """ISSUE 19 lifts the members==1 preemption gate: the victim range is
    member-LOCAL (flat row m·n_slots+s), so an interactive arrival on
    member 0 parks only member 0's batch resident — the bystander stream
    on member 1 is never preempted — and both streams stay token-for-token
    identical to their solo runs (per-member replay bookkeeping)."""
    eng = InferenceEngine(SPEC, seed=0, n_slots=1, decode_chunk=4,
                          qos=True, members=2)
    try:
        victim_ids = [11, 13, 17, 19, 23, 29]
        by_ids = [31, 37, 41, 43]
        solo_v = list(eng.stream_results(eng.submit(
            list(victim_ids), max_new_tokens=40, sampler=sampler,
            seed=5, member=0)))
        solo_b = list(eng.stream_results(eng.submit(
            list(by_ids), max_new_tokens=30, sampler=sampler,
            seed=3, member=1)))
        before = eng.n_preemptions
        victim = eng.submit(list(victim_ids), max_new_tokens=40,
                            sampler=sampler, seed=5, priority="batch",
                            member=0)
        bystander = eng.submit(list(by_ids), max_new_tokens=30,
                               sampler=sampler, seed=3, priority="batch",
                               member=1)
        got_v: list = []
        got_b: list = []
        th_v = threading.Thread(target=_drain, args=(eng, victim, got_v),
                                daemon=True)
        th_b = threading.Thread(target=_drain, args=(eng, bystander, got_b),
                                daemon=True)
        th_v.start()
        th_b.start()
        deadline = time.time() + 60
        while victim.emitted < 8 and time.time() < deadline:
            time.sleep(0.005)
        assert victim.emitted >= 8, "victim never reached mid-decode"
        bene = eng.submit([41, 43, 47], max_new_tokens=6, sampler=sampler,
                          seed=9, priority="interactive", member=0)
        bene_got = list(eng.stream_results(bene))
        th_v.join(120)
        th_b.join(120)
        assert not th_v.is_alive() and not th_b.is_alive()
        assert len(bene_got) == 6
        # exactly ONE preemption, and it hit member 0's resident
        assert eng.n_preemptions == before + 1
        assert victim.n_preempts == 1 and bystander.n_preempts == 0
        assert got_v == solo_v, (len(got_v), len(solo_v))
        assert got_b == solo_b, (len(got_b), len(solo_b))
    finally:
        eng.shutdown()


@slow
def test_qos_not_in_engine_cache_key_and_opt_in_wins():
    """The cache-key pin: a qos=0 and a qos=1 backend over the same
    checkpoint share ONE engine (qos is pure host policy — no program or
    weight difference), and any opt-in flips the shared flag."""
    spec = dataclasses.replace(SPEC, max_seq=96)  # private cache row
    e_off = get_engine(spec, seed=7, n_slots=1, qos=False)
    e_on = get_engine(spec, seed=7, n_slots=1, qos=True)
    try:
        assert e_on is e_off
        assert e_off.qos is True  # the explicit opt-in won
        # and a later qos=False caller cannot un-opt the shared engine
        assert get_engine(spec, seed=7, n_slots=1, qos=False).qos is True
    finally:
        e_off.shutdown()


@slow
def test_submit_rejects_unknown_priority():
    eng = InferenceEngine(SPEC, seed=0, n_slots=1, qos=True)
    try:
        with pytest.raises(ValueError, match="priority"):
            eng.submit([3, 4, 5], max_new_tokens=4, sampler=GREEDY,
                       priority="urgent")
    finally:
        eng.shutdown()


@slow
def test_shed_mapping_deadline_breaker_queue_full():
    """_raise_shed maps the cost model's decisions onto the engine's
    typed exceptions: expired deadline -> DeadlineExceeded("queue"),
    open breaker -> EngineBreakerOpen, capacity -> QueueFullError with a
    dynamic retry_after the HTTP layer forwards as Retry-After."""
    eng = InferenceEngine(SPEC, seed=0, n_slots=1, max_pending=1, qos=True)
    try:
        with pytest.raises(DeadlineExceeded):
            eng.submit([3, 4, 5], max_new_tokens=4, sampler=GREEDY,
                       deadline=time.monotonic() - 1.0)
        # fill the slot and the 1-deep queue, then overflow it (early
        # submits may admit before later ones arrive; keep pushing and
        # keep every accepted handle so the drain below is complete)
        cancel = threading.Event()
        held = []
        with pytest.raises(QueueFullError) as exc:
            while True:
                held.append(eng.submit([5, 6, 7] * 8, max_new_tokens=64,
                                       sampler=GREEDY, cancel=cancel))
        assert exc.value.retry_after >= 1.0
        assert "admission queue full" in str(exc.value)
        cancel.set()
        for r in held:
            for _ in eng.stream_results(r):
                pass
        # breaker: open it (threshold failures in-window) and expect the
        # typed rejection
        now = time.monotonic()
        for _ in range(eng.breaker.threshold):
            eng.breaker.record_failure(now)
        with pytest.raises(EngineBreakerOpen):
            eng.submit([3, 4], max_new_tokens=2, sampler=GREEDY)
    finally:
        eng.shutdown()


@slow
def test_predictive_shed_end_to_end():
    """With warm EWMAs, live queue pressure, and a hopeless deadline, the
    engine sheds at submit (DeadlineExceeded -> 503 queue stage) instead
    of letting the request time out in line."""
    eng = InferenceEngine(SPEC, seed=0, n_slots=1, qos=True)
    try:
        for _ in range(MIN_OBS):  # warm the evidence
            eng.cost_model.observe_queue_wait(2.0)
            eng.cost_model.observe_service(3.0)
        cancel = threading.Event()
        occupant = eng.submit([5, 6, 7] * 6, max_new_tokens=64,
                              sampler=GREEDY, cancel=cancel)
        waiter = eng.submit([6, 7, 8] * 6, max_new_tokens=8,
                            sampler=GREEDY, cancel=cancel)
        before = eng.cost_model.n_predictive_sheds
        with pytest.raises(DeadlineExceeded):
            eng.submit([9, 10, 11], max_new_tokens=4, sampler=GREEDY,
                       deadline=time.monotonic() + 0.5)
        assert eng.cost_model.n_predictive_sheds == before + 1
        cancel.set()
        for r in (occupant, waiter):
            for _ in eng.stream_results(r):
                pass
    finally:
        eng.shutdown()


@slow
def test_qos_off_is_fifo_and_inert():
    """The default path: qos=0 admits in submit order (no policy pick),
    exports qos=0, and never counts preemptions."""
    eng = InferenceEngine(SPEC, seed=0, n_slots=2)
    try:
        outs = {}

        def run(i):
            outs[i] = list(eng.generate_stream(
                [3 + i, 4 + i, 5 + i], max_new_tokens=6, sampler=GREEDY,
                seed=i))
        ths = [threading.Thread(target=run, args=(i,)) for i in range(6)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(120)
        assert len(outs) == 6
        m = eng.metrics()
        assert m["qos"] == 0
        assert m["preemptions_total"] == 0
        assert m["predictive_sheds_total"] == 0
    finally:
        eng.shutdown()
