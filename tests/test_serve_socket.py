"""Socket-level streaming tests: incremental delivery through the real server.

Round 1's benchmark drove the app through httpx.ASGITransport, which buffers
the entire ASGI response before the client sees byte one — so TTFT silently
equaled total latency and nothing caught it (VERDICT.md round 1, weakness 2).
These tests pin the property that matters: through the bundled h11 server on
a real TCP socket, the first SSE content delta arrives while the rest of the
stream is still being produced.
"""

from __future__ import annotations

import asyncio
import json
import time

import httpx
import pytest

from quorum_tpu.backends.fake import FakeBackend
from quorum_tpu.config import Config
from quorum_tpu.server.app import create_app
from quorum_tpu.server.serve import start_server

from tests.conftest import two_backend_parallel_config

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

N_CHUNKS = 5
CHUNK_DELAY = 0.08
# A stream of N chunks spaced CHUNK_DELAY apart takes ~N*CHUNK_DELAY end to
# end; genuinely incremental delivery puts the first delta ~1 chunk in. The
# 0.5 threshold leaves slack for slow CI while still failing hard on any
# buffer-the-whole-response regression (where ttft == total).
MAX_TTFT_FRACTION = 0.5


def single_backend_config() -> dict:
    return {
        "settings": {"timeout": 10},
        "primary_backends": [
            {"name": "LLM1", "url": "http://test1.example.com/v1", "model": "m"}
        ],
    }


def _delta_content(line: str) -> str | None:
    """Extract one SSE line's content delta, or None for non-content lines."""
    if not line.startswith("data: ") or line == "data: [DONE]":
        return None
    delta = (json.loads(line[6:]).get("choices") or [{}])[0].get("delta") or {}
    return delta.get("content")


async def _stream_timing(app, body) -> tuple[float, float]:
    """Drive one streaming request over a real socket; return (ttft, total)."""
    server = await start_server(app, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    try:
        async with httpx.AsyncClient(
            base_url=f"http://127.0.0.1:{port}", timeout=30
        ) as client:
            t0 = time.perf_counter()
            ttft = None
            async with client.stream(
                "POST", "/chat/completions", json=body,
                headers={"Authorization": "Bearer t"},
            ) as resp:
                assert resp.status_code == 200
                async for line in resp.aiter_lines():
                    if ttft is None and _delta_content(line):
                        ttft = time.perf_counter() - t0
            total = time.perf_counter() - t0
    finally:
        server.close()
        await server.wait_closed()
    assert ttft is not None, "no content delta received"
    return ttft, total


def _slow_backends(names: tuple[str, ...]) -> dict[str, FakeBackend]:
    return {
        name: FakeBackend(
            name, chunks=["tok"] * N_CHUNKS, chunk_delay=CHUNK_DELAY,
            requires_auth=False,
        )
        for name in names
    }


def _single_app():
    return create_app(
        Config(raw=single_backend_config()), **_slow_backends(("LLM1",))
    )


def _parallel_app():
    return create_app(
        Config(raw=two_backend_parallel_config()),
        **_slow_backends(("LLM1", "LLM2")),
    )


@pytest.mark.parametrize("app_factory", [_single_app, _parallel_app],
                         ids=["single", "parallel"])
async def test_stream_is_incremental_over_socket(app_factory):
    body = {"model": "m", "messages": [{"role": "user", "content": "hi"}],
            "stream": True}
    ttft, total = await _stream_timing(app_factory(), body)
    assert total >= N_CHUNKS * CHUNK_DELAY * 0.8
    assert ttft < total * MAX_TTFT_FRACTION, (
        f"first delta at {ttft:.3f}s of {total:.3f}s — stream is buffered"
    )


async def test_int8_prefix_cached_serving_over_socket():
    """Integration of the round-3 features through the FULL stack: an
    int8-quantized local model behind a real TCP socket, streaming SSE, with
    the second identical request hitting the prefix cache — and /metrics
    exporting the hit counters."""
    raw = {
        "settings": {"timeout": 60},
        "primary_backends": [
            {"name": "Q8",
             "url": "tpu://llama-tiny?quant=int8&max_seq=128"
                    "&prefill_chunk=16&seed=3",
             "model": "llama-tiny"},
        ],
    }
    app = create_app(Config(raw=raw))
    server = await start_server(app, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    body = {
        "model": "llama-tiny",
        "messages": [{"role": "user",
                      "content": "please repeat this long shared preamble "
                                 "once more for the integration test"}],
        "stream": True,
        "max_tokens": 4,
        "temperature": 0,
    }
    try:
        async with httpx.AsyncClient(
            base_url=f"http://127.0.0.1:{port}", timeout=60
        ) as client:

            async def one() -> str:
                text = []
                async with client.stream(
                    "POST", "/chat/completions", json=body,
                    headers={"Authorization": "Bearer t"},
                ) as resp:
                    assert resp.status_code == 200
                    async for line in resp.aiter_lines():
                        piece = _delta_content(line)
                        if piece:
                            text.append(piece)
                return "".join(text)

            first = await one()
            second = await one()
            assert first == second, "greedy repeat diverged"
            metrics = (await client.get("/metrics")).text
    finally:
        server.close()
        await server.wait_closed()
    assert 'quorum_tpu_engine_prefix_hits_total{backend="Q8"} 1' in metrics
    saved = [line for line in metrics.splitlines()
             if line.startswith("quorum_tpu_engine_prefix_tokens_saved_total")]
    assert saved and int(saved[0].rsplit(" ", 1)[1]) >= 16


async def test_client_disconnect_frees_slot_and_counts_cancellation():
    """A client that drops its SSE connection mid-stream must not pin the
    engine slot for the rest of its max_tokens budget: the engine retires
    the request within a chunk boundary (slot freed for the next request)
    and /metrics counts the cancellation. slots=1 makes reclamation
    observable — a follow-up request can only be served from the freed
    slot."""
    raw = {
        "settings": {"timeout": 60},
        "primary_backends": [
            {"name": "T",
             "url": "tpu://gpt2-tiny?max_seq=2048&slots=1&decode_chunk=4"
                    "&max_tokens=1500",
             "model": "gpt2-tiny"},
        ],
    }
    app = create_app(Config(raw=raw))
    server = await start_server(app, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    long_body = {
        "model": "gpt2-tiny",
        "messages": [{"role": "user", "content": "stream a very long answer"}],
        "stream": True, "max_tokens": 1500, "temperature": 0.8,
    }
    try:
        async with httpx.AsyncClient(
            base_url=f"http://127.0.0.1:{port}", timeout=60
        ) as client:
            # Start the long stream and abandon it after the first delta.
            async with client.stream(
                "POST", "/chat/completions", json=long_body,
                headers={"Authorization": "Bearer t"},
            ) as resp:
                assert resp.status_code == 200
                async for line in resp.aiter_lines():
                    if _delta_content(line):
                        break  # exit the context = drop the connection

            # The freed slot must serve a fresh request to completion, and
            # the cancellation must be counted. Poll briefly: teardown of
            # the dropped request propagates asynchronously (client close →
            # ASGI task cancel → engine cancel event → chunk boundary).
            deadline = time.time() + 30
            counted = False
            while time.time() < deadline and not counted:
                metrics = (await client.get("/metrics")).text
                counted = "quorum_tpu_engine_cancellations_total" in metrics and any(
                    line.split()[-1] not in ("0", "0.0")
                    for line in metrics.splitlines()
                    if line.startswith("quorum_tpu_engine_cancellations_total"))
                if not counted:
                    await asyncio.sleep(0.3)
            assert counted, "cancellation never counted after client drop"

            short = dict(long_body, max_tokens=4, stream=False)
            t0 = time.time()
            r = await client.post("/chat/completions", json=short,
                                  headers={"Authorization": "Bearer t"})
            assert r.status_code == 200
            assert r.json()["usage"]["completion_tokens"] >= 1
            # Well under the dropped request's 1500-token budget worth of
            # decode time: the slot was reclaimed, not waited out.
            assert time.time() - t0 < 25
    finally:
        server.close()
        await server.wait_closed()
