"""Sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4(c)).

Verifies that the TP/DP-sharded model produces the same numbers as the
single-device run, that parameter layouts follow the Megatron rules, and
that the MoE experts axis shards over tp (expert parallelism).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from quorum_tpu.models import init_params, prefill, resolve_spec
from quorum_tpu.models.transformer import decode_step, init_cache
from quorum_tpu.parallel import MeshConfig, make_mesh, shard_pytree
from quorum_tpu.parallel.sharding import (
    kv_cache_sharding,
    param_partition_specs,
)

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def test_mesh_shapes():
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    assert mesh.shape == {"dp": 2, "pp": 1, "sp": 1, "tp": 4}
    assert len(mesh.devices.flatten()) == 8


def test_param_partition_specs_follow_megatron_rules():
    spec = resolve_spec("mixtral-tiny")
    params = init_params(spec, seed=0)
    specs = param_partition_specs(params)
    blocks = specs["blocks"]
    # The leading scanned-layer dim stage-shards over pp (a no-op placement
    # on every mesh whose pp axis is 1; the pipeline-staged decode group's
    # stages each hold L/pp layers — docs/scaling.md).
    assert blocks["wq"] == P("pp", None, "tp")     # project-in: shard output
    assert blocks["wo"] == P("pp", "tp", None)     # project-out: shard input
    assert blocks["router"] == P("pp", None, "tp")  # router over experts axis
    assert blocks["moe_w_up"] == P("pp", "tp", None, None)  # experts over tp (EP)
    assert specs["tok_emb"] == P("tp", None)       # vocab-sharded embedding
    assert blocks["attn_norm_w"] == P("pp", None)  # norms replicated within a stage


def _run(spec, params, mesh=None):
    toks = jnp.array([[5, 6, 7, 8, 0, 0], [9, 10, 0, 0, 0, 0]], dtype=jnp.int32)
    lengths = jnp.array([4, 2], dtype=jnp.int32)
    ck, cv = init_cache(spec, 2)
    if mesh is not None:
        params = shard_pytree(mesh, params)
        kv_sh = kv_cache_sharding(mesh, spec.n_kv_heads, batch=2)
        ck, cv = jax.device_put(ck, kv_sh), jax.device_put(cv, kv_sh)
    pf = jax.jit(prefill, static_argnums=(1,), donate_argnums=(4, 5))
    logits, ck, cv = pf(params, spec, toks, lengths, ck, cv)
    ds = jax.jit(decode_step, static_argnums=(1,), donate_argnums=(4, 5))
    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
    dlogits, ck, cv = ds(params, spec, nxt, lengths, ck, cv)
    return np.asarray(jax.device_get(logits)), np.asarray(jax.device_get(dlogits))


def test_tp_dp_sharded_matches_single_device():
    spec = resolve_spec("llama-tiny", {"n_kv_heads": "4"})
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    l_sh, d_sh = _run(spec, init_params(spec, 0), mesh)
    l_1, d_1 = _run(spec, init_params(spec, 0))
    np.testing.assert_allclose(l_sh, l_1, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(d_sh, d_1, rtol=2e-2, atol=2e-2)


# De-quarantined (PR 17): the PR 16 divergence was a GSPMD miscompile in
# the grouped dispatch's expert-buffer gather (a gather from a concat of a
# dp-sharded token matrix with a replicated pad row reads the wrong shard
# on jax 0.4.x) — fixed in models/transformer.py by the clamp-index+mask
# formulation.
def test_moe_expert_parallel_matches_single_device():
    spec = resolve_spec("mixtral-tiny")  # 4 experts over tp=4
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    l_sh, d_sh = _run(spec, init_params(spec, 0), mesh)
    l_1, d_1 = _run(spec, init_params(spec, 0))
    np.testing.assert_allclose(l_sh, l_1, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(d_sh, d_1, rtol=2e-2, atol=2e-2)


def test_full_tp8_sharding():
    spec = resolve_spec("llama-tiny", {"n_heads": "8", "n_kv_heads": "8", "d_model": "64"})
    mesh = make_mesh(MeshConfig(tp=8))
    l_sh, _ = _run(spec, init_params(spec, 0), mesh)
    l_1, _ = _run(spec, init_params(spec, 0))
    np.testing.assert_allclose(l_sh, l_1, rtol=2e-2, atol=2e-2)
