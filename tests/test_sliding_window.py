"""Sliding-window attention (mistral): every attention path honors
``ModelSpec.sliding_window``.

The strongest pin is HF parity: a tiny MistralForCausalLM with a window
SMALLER than the sequence, logits matched against transformers' own SWA
masking — if any path silently computed full causal attention, the tail
tokens (which must NOT see the early ones) would diverge. Internal
consistency then pins that the cache-free forward, the admission prefill +
decode engine path, chunked prefill, the Pallas kernels, and the int8 KV
path all agree with each other under a window.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.ops.attention import decode_attention, prefill_attention
from quorum_tpu.ops.sampling import SamplerConfig

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

GREEDY = SamplerConfig(temperature=0.0, top_p=1.0)
WSPEC = {"n_kv_heads": "4", "max_seq": "128", "sliding_window": "16"}


def test_hf_mistral_sliding_window_parity(tmp_path):
    import torch
    from transformers import MistralConfig, MistralForCausalLM

    torch.manual_seed(0)
    cfg = MistralConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0,
        sliding_window=8, attn_implementation="eager",
        tie_word_embeddings=False,
    )
    model = MistralForCausalLM(cfg).eval()
    model.save_pretrained(tmp_path, safe_serialization=True)

    from quorum_tpu.models.hf_loader import load_hf_checkpoint
    from quorum_tpu.models.transformer import forward_logits

    spec, params = load_hf_checkpoint(tmp_path)
    assert spec.sliding_window == 8, "loader dropped the config's window"

    tokens = np.arange(3, 27, dtype=np.int64)[None, :]  # 24 > window 8
    with torch.no_grad():
        theirs = model(torch.tensor(tokens)).logits.float().numpy()
    ours = np.asarray(
        forward_logits(params, spec, jnp.asarray(tokens, jnp.int32)),
        np.float32)
    np.testing.assert_allclose(ours, theirs, rtol=2e-2, atol=5e-3)  # bf16 load
    # and the window genuinely matters at this length: a windowless load
    # must NOT match the tail of the sequence.
    import dataclasses

    full = np.asarray(forward_logits(
        params, dataclasses.replace(spec, sliding_window=0),
        jnp.asarray(tokens, jnp.int32)), np.float32)
    assert np.abs(full[:, -1] - theirs[:, -1]).max() > 1e-3, (
        "window had no effect — test sequence too short?")


def test_engine_decode_matches_cache_free_forward():
    """Greedy generation through the engine (prefill + windowed decode over
    the cache) must equal argmax continuation of the cache-free windowed
    forward — pinning that BOTH paths apply the same window."""
    from quorum_tpu.models.init import init_params
    from quorum_tpu.models.transformer import forward_logits

    spec = resolve_spec("llama-tiny", WSPEC)
    params = init_params(spec, seed=3)
    prompt = [(i % 97) + 3 for i in range(40)]  # 40 > window 16

    eng = InferenceEngine(spec, params=jax.tree.map(np.asarray, params),
                         decode_chunk=4, n_slots=2)
    got = eng.generate(prompt, max_new_tokens=8, sampler=GREEDY).token_ids
    eng.shutdown()

    toks = list(prompt)
    for _ in range(8):
        logits = forward_logits(params, spec, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    assert got == toks[len(prompt):], (
        "engine decode disagrees with the cache-free windowed forward")


def test_chunked_prefill_and_prefix_cache_respect_window():
    """Long prompts admitted in segments (and re-admitted over a cached
    prefix) must produce the same windowed continuation."""
    spec = resolve_spec("llama-tiny", WSPEC)
    prompt = [(i % 89) + 3 for i in range(50)]

    whole = InferenceEngine(spec, decode_chunk=4, n_slots=2, seed=3)
    ref = whole.generate(prompt, max_new_tokens=6, sampler=GREEDY).token_ids
    whole.shutdown()

    chunked = InferenceEngine(spec, decode_chunk=4, n_slots=2, seed=3,
                              prefill_chunk=16)
    got = chunked.generate(prompt, max_new_tokens=6, sampler=GREEDY).token_ids
    warm = chunked.generate(prompt, max_new_tokens=6, sampler=GREEDY).token_ids
    chunked.shutdown()
    assert got == ref and warm == ref


def test_flash_kernels_match_reference_with_window():
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    b, h, n_kv, t, hd = 2, 8, 4, 256, 64
    from quorum_tpu.ops.flash_attention import flash_prefill_attention
    from quorum_tpu.ops.flash_decode import flash_decode_attention

    # prefill kernel
    q = jax.random.normal(ks[0], (b, h, t, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, n_kv, t, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, n_kv, t, hd), jnp.float32)
    lengths = jnp.array([256, 100], jnp.int32)
    ref = prefill_attention(q, k, v, lengths, window=32)
    got = flash_prefill_attention(q, k, v, lengths, block_q=128, block_k=128,
                                  interpret=True, window=32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    # decode kernel
    qd = jax.random.normal(ks[0], (b, h, 1, hd), jnp.float32)
    dlen = jnp.array([200, 7], jnp.int32)
    refd = decode_attention(qd, k, v, dlen, window=32)
    gotd = flash_decode_attention(qd, k, v, dlen, block_k=128,
                                  interpret=True, window=32)
    np.testing.assert_allclose(np.asarray(gotd), np.asarray(refd),
                               rtol=2e-5, atol=2e-5)


def test_int8_kv_and_spec_decode_respect_window():
    """kv_quant=int8 decode and speculative verification run the same
    window: both must reproduce the plain windowed engine's output."""
    spec = resolve_spec("llama-tiny", WSPEC)
    prompt = [(i % 83) + 3 for i in range(30)]

    plain = InferenceEngine(spec, decode_chunk=4, n_slots=2, seed=5)
    ref = plain.generate(prompt, max_new_tokens=8, sampler=GREEDY).token_ids
    plain.shutdown()

    q8 = InferenceEngine(spec, decode_chunk=4, n_slots=2, seed=5,
                         kv_quant="int8")
    got8 = q8.generate(prompt, max_new_tokens=8, sampler=GREEDY).token_ids
    q8.shutdown()
    # int8 rounding can flip near-tie argmaxes; require high agreement and
    # identical prefixes rather than exact equality.
    agree = sum(a == b for a, b in zip(got8, ref))
    assert agree >= 6, (got8, ref)

    spec_eng = InferenceEngine(spec, decode_chunk=4, n_slots=2, seed=5,
                               spec_decode=4)
    gots = spec_eng.generate(prompt, max_new_tokens=8, sampler=GREEDY).token_ids
    spec_eng.shutdown()
    assert gots == ref, "speculative verification ignored the window"


def test_sp_mesh_rejects_windowed_spec():
    from quorum_tpu.parallel import MeshConfig, make_mesh

    spec = resolve_spec("llama-tiny", WSPEC)
    mesh = make_mesh(MeshConfig(sp=2))
    with pytest.raises(ValueError, match="sliding_window"):
        InferenceEngine(spec, mesh)


def test_stacked_members_respect_window():
    """members=M stacks windowed engines member-vmapped; each member's
    stream must equal its own per-seed single engine."""
    spec = resolve_spec("llama-tiny", WSPEC)
    prompt = [(i % 79) + 3 for i in range(40)]
    stacked = InferenceEngine(spec, members=2, decode_chunk=4, n_slots=2)
    singles = [InferenceEngine(spec, seed=i, decode_chunk=4, n_slots=2)
               for i in range(2)]
    try:
        for m in range(2):
            a = stacked.generate(prompt, max_new_tokens=8, sampler=GREEDY,
                                 seed=9, member=m).token_ids
            b = singles[m].generate(prompt, max_new_tokens=8, sampler=GREEDY,
                                    seed=9).token_ids
            assert a == b, f"member {m} diverged under the window"
    finally:
        stacked.shutdown()
        for s in singles:
            s.shutdown()
