"""Spec decode that composes (ISSUE 10 acceptance): grammar-aware drafts,
per-row gating, ring-resident verify.

The invariant is unchanged — a draft token is accepted ONLY when it equals
the token the model itself samples at that position, so speculation changes
speed, never content. What is new here:

- **grammar-aware speculation**: constrained (response_format) rows ride
  verify dispatches through the dfa-verify variant — each position's logits
  masked by its draft-prefix DFA state — pinned token-for-token against the
  non-speculative constrained stream at K=4·C=4, greedy and sampled;
- **per-row gating**: one penalized/logprobs row no longer turns
  speculation off for the batch — it rides the same dispatch at draft
  length 0 (one token per dispatch) while clean rows accept more;
- **ring-resident verify**: verify dispatches enter the decode_pipeline=K
  ring instead of draining it — pipelined drafts come from the optimistic
  source-continuation cursor, and the dispatch-counter acceptance shows
  sustained in-flight depth >= K-1 through pure spec traffic;
- **containment**: a failed verify dispatch (faults site ``engine.verify``)
  dooms only its own turn's rows; pending requests keep their place and
  the engine keeps serving.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from quorum_tpu.analysis import budget
from quorum_tpu.constrain import compile_response_format
from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.engine.tokenizer import ByteTokenizer
from quorum_tpu.models.model_config import MODEL_PRESETS
from quorum_tpu.ops.sampling import SamplerConfig

pytestmark = pytest.mark.slow

TINY = MODEL_PRESETS["llama-tiny"]
TOK = ByteTokenizer(TINY.vocab_size)
GREEDY = SamplerConfig(temperature=0.0)
SCHEMA = {"type": "object", "properties": {
    "ok": {"type": "boolean"},
    "n": {"type": "integer"}}}


def _grammar():
    rf = {"type": "json_schema", "json_schema": {"schema": SCHEMA}}
    return compile_response_format(rf, TOK, TINY.vocab_size)


def _run_constrained(eng, grammar, *, temp, seed, max_new=48):
    req = eng.submit(
        TOK.encode("go"), max_new_tokens=max_new,
        sampler=SamplerConfig(temperature=temp), seed=seed,
        eos_id=TOK.eos_id, grammar=grammar)
    return list(eng.stream_results(req))


def _oracle(eng, ref):
    """Install oracle drafting: propose the reference continuation."""
    body = [t for t in ref if t != TOK.eos_id]
    eng._draft = lambda req, g: (
        body[req.emitted: req.emitted + g]
        if req.emitted + g <= len(body) else None)


def test_constrained_spec_pin_at_k4_c4_greedy_and_sampled():
    """Acceptance pin (a): constrained + spec_decode vs non-speculative
    constrained at decode_pipeline=4 · decode_loop=4, token for token,
    greedy AND sampled — with drafts genuinely accepted (oracle)."""
    plain = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=4,
                            decode_loop=4)
    spec = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=4,
                           decode_loop=4, spec_decode=4)
    try:
        g = _grammar()
        for temp, seed in ((0.0, 3), (0.8, 11)):
            want = _run_constrained(plain, g, temp=temp, seed=seed)
            _oracle(spec, want)
            acc0 = spec.n_spec_accepted
            got = _run_constrained(spec, g, temp=temp, seed=seed)
            assert got == want, (
                f"temp={temp}: constrained spec stream diverged")
            assert spec.n_spec_accepted > acc0, (
                f"temp={temp}: no draft accepted under the grammar")
        fams = budget.decode_families(spec._decode_cache)
        assert "dfa_verify" in fams, fams
    finally:
        plain.shutdown()
        spec.shutdown()


def test_mixed_batch_per_row_gating_pin():
    """Acceptance pin (b): a mixed batch — clean + penalized + constrained
    rows co-batched on one spec engine — matches the non-speculative
    engine row for row, with the clean row accepting >1 token per
    dispatch while the penalized row advances 1/dispatch (its draft
    length is 0 by gating, not by batch exclusion)."""
    grammar = _grammar()
    sampler = SamplerConfig(temperature=0.8, top_p=0.9)

    def jobs(eng):
        def clean():
            return eng.generate([7, 7, 7, 7, 7, 7], max_new_tokens=20,
                                sampler=GREEDY, seed=0).token_ids

        def penalized():
            req = eng.submit([5, 6, 7, 5, 6, 7], max_new_tokens=20,
                             sampler=sampler, seed=3,
                             frequency_penalty=1.5)
            return list(eng.stream_results(req))

        def constrained():
            return _run_constrained(eng, grammar, temp=0.8, seed=9,
                                    max_new=20)

        with ThreadPoolExecutor(max_workers=3) as ex:
            fs = [ex.submit(f) for f in (clean, penalized, constrained)]
            return [f.result() for f in fs]

    plain = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=4,
                            n_slots=3)
    want = jobs(plain)
    plain.shutdown()

    spec = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=4,
                           n_slots=3, spec_decode=4)
    # Oracle for the clean row only: its draft is its own reference
    # continuation, so it accepts while the penalized row (gated to draft
    # length 0) rides the same dispatches one token at a time.
    clean_ref = want[0]
    real_draft = InferenceEngine._draft

    def draft(req, g):
        if req.hist[: 6] == [7, 7, 7, 7, 7, 7] and req.pp == 0.0 \
                and req.fp == 0.0 and req.grammar is None:
            return (clean_ref[req.emitted: req.emitted + g]
                    if req.emitted + g <= len(clean_ref) else None)
        return real_draft(req, g)

    spec._draft = draft
    got = jobs(spec)
    m = spec.metrics()
    spec.shutdown()
    assert got == want, "mixed batch diverged from the non-speculative runs"
    assert m["spec_turns_total"] > 0
    # the clean row accepted >1 token on some dispatch while the penalized
    # row rode along: accepted > 0 proves multi-token turns happened in a
    # batch that CONTAINED ineligible rows (the old all-rows gate would
    # have forced every dispatch to the chunked path).
    assert m["spec_accepted_total"] > 0


def test_logprobs_row_rides_spec_dispatches():
    """A logprobs request on a spec engine (draft length 0) still gets one
    lp record per token, equal to the non-speculative engine's within
    float-reassociation tolerance, with tokens exact."""
    def run(eng):
        req = eng.submit([7, 7, 7, 7, 7], max_new_tokens=12,
                         sampler=GREEDY, seed=0, logprobs=3)
        toks = list(eng.stream_results(req))
        return toks, [lp for lp, _, _ in req.lp]

    plain = InferenceEngine(TINY, decode_chunk=4, n_slots=2)
    want_t, want_lp = run(plain)
    plain.shutdown()

    spec = InferenceEngine(TINY, decode_chunk=4, n_slots=2, spec_decode=4)
    # another clean row co-batches and drafts, forcing verify dispatches
    def side():
        spec.generate([9, 8, 9, 8, 9, 8, 9, 8], max_new_tokens=24,
                      sampler=GREEDY, seed=1)

    t = threading.Thread(target=side)
    t.start()
    got_t, got_lp = run(spec)
    t.join()
    m = spec.metrics()
    spec.shutdown()
    assert got_t == want_t
    assert len(got_lp) == len(got_t)
    np.testing.assert_allclose(got_lp, want_lp, atol=2e-3)
    assert m["spec_turns_total"] >= 0  # speculation may or may not engage


def test_pipelined_cursor_alignment_beyond_period_1():
    """The optimistic cursor skips exactly ONE undrafted position per
    pipelined turn — the bonus token; the next turn's first draft proposes
    that turn's own first sample. On a period-6 source the pipelined
    drafts must continue the periodic text exactly (an off-by-one here is
    invisible on the period-1 bias streams but rejects position 0 of
    every pipelined draft on real repetitive text)."""
    from quorum_tpu.engine.engine import _Request

    eng = InferenceEngine.__new__(InferenceEngine)  # only _form_draft
    req = _Request([1, 2, 3, 4, 5, 6, 1, 2], 64, GREEDY, 0, None, None,
                   None)
    d = eng._form_draft(req, 4)  # fresh: continuation of pair (1,2)
    assert d == [3, 4, 5, 6]
    assert req.spec_state is not None
    req.n_inflight = 1
    # turn 1 optimistically emits d + bonus (1): the stream is
    # ...5,6,1,2 | 3,4,5,6,1 — turn 2 then drafts [2,3,4,5], turn 3
    # [1,2,3,4], each continuing the period-6 text.
    assert eng._form_draft(req, 4) == [2, 3, 4, 5]
    assert eng._form_draft(req, 4) == [1, 2, 3, 4]
    assert eng._form_draft(req, 4) == [6, 1, 2, 3]


def test_ring_stays_full_through_spec_traffic():
    """Acceptance pin: verify turns no longer drain decode_pipeline=K.
    A logit_bias-forced periodic stream (bias rows ARE draft-eligible)
    keeps the prompt-lookup cursor drafting pipelined turns, and the
    dispatch counters show sustained in-flight depth >= K-1."""
    k = 4
    eng = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=k,
                          n_slots=2, spec_decode=4)
    depths = []
    orig = InferenceEngine._reap_oldest

    def probe(self):
        depths.append(len(self._inflight))
        return orig(self)

    eng._reap_oldest = probe.__get__(eng)
    bias = np.zeros((TINY.vocab_size,), np.float32)
    bias[7] = 1e9  # greedy emits token 7 forever: period-1 repetition

    def run():
        req = eng.submit([7, 7, 7, 7], max_new_tokens=64, sampler=GREEDY,
                         seed=0, logit_bias=bias)
        return list(eng.stream_results(req))

    run()  # warm every (depth, history-bucket) verify program
    depths.clear()
    t0, o0 = eng.n_spec_turns, eng.n_spec_overlapped
    out = run()
    m = eng.metrics()
    eng.shutdown()
    assert out == [7] * 64
    turns = m["spec_turns_total"] - t0
    overlapped = m["spec_overlapped_total"] - o0
    assert turns > 4, m
    # The dispatch-counter acceptance: most speculative dispatches were
    # issued onto a NON-EMPTY ring (the pre-PR engine drained it for every
    # verify turn, so this was structurally zero)...
    assert overlapped >= turns // 2, (depths, turns, overlapped)
    # ...and the ring genuinely reaches full depth K with only verify
    # turns in it, holding >= K-1 in front of the blocking reap for a
    # majority of steady-state turns (the tail drains as budgets end).
    assert max(depths) >= k, depths
    steady = depths[: -k] if len(depths) > k else depths
    deep = sum(1 for d in steady if d >= k - 1)
    assert deep / max(1, len(steady)) >= 0.5, (
        f"ring not sustained through spec traffic: depths={depths}")


def test_spec_engine_unchanged_paths_compile_preexisting_keys():
    """A spec engine whose traffic never drafts dispatches the EXACT
    pre-existing chunk program families — speculation must cost nothing
    until a draft exists."""
    eng = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=2,
                          spec_decode=4)
    try:
        # distinct non-repeating tokens: no 2-gram recurrence, no drafts
        eng.generate(list(range(7, 27)), max_new_tokens=8, sampler=GREEDY)
        fams = budget.decode_families(eng._decode_cache)
        assert fams == {"plain"}, fams
        # repetitive traffic then adds ONLY verify-family programs
        eng.generate([9, 8, 9, 8, 9, 8, 9, 8], max_new_tokens=16,
                     sampler=GREEDY)
        fams = budget.decode_families(eng._decode_cache)
        assert fams <= {"plain", "verify"}, fams
    finally:
        eng.shutdown()


def test_verify_fault_dooms_only_its_turn():
    """faults site ``engine.verify``: a failed speculative dispatch dooms
    the rows of that turn only — pending requests keep their place, no
    rebuild is counted, and the engine keeps serving."""
    from quorum_tpu import faults

    eng = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=2,
                          n_slots=1, spec_decode=4)
    bias = np.zeros((TINY.vocab_size,), np.float32)
    bias[7] = 1e9  # forced periodic stream: drafts form on every turn

    def run():
        req = eng.submit([7, 7, 7, 7], max_new_tokens=12, sampler=GREEDY,
                         seed=0, logit_bias=bias)
        return list(eng.stream_results(req))

    try:
        ref = run()
        assert eng.n_spec_turns > 0  # the workload really speculates
        rebuilds0 = eng.n_rebuilds
        faults.arm("engine.verify", times=1)
        try:
            victim = eng.submit([7, 7, 7, 7], max_new_tokens=12,
                                sampler=GREEDY, seed=0, logit_bias=bias)
            with pytest.raises(faults.FaultInjected):
                list(eng.stream_results(victim))
        finally:
            faults.disarm()
        # the engine serves again immediately, identically, no rebuild
        assert run() == ref
        assert eng.n_rebuilds == rebuilds0, (
            "a contained verify fault must not rebuild device state")
    finally:
        eng.shutdown()
