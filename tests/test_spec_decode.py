"""Speculative decoding (prompt-lookup drafts + multi-token verification).

The invariant that makes speculation safe: a draft token is accepted ONLY
when it equals the token the model itself emits at that position — sampled
with the request's own RNG chain (greedy = argmax) — so the output is
bit-identical to the non-speculative path's, at any temperature;
speculation changes speed, never content. These tests pin output equality against the non-speculative
engine, eligibility gating, and the repetitive-text acceptance win.
"""

import numpy as np

from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.ops.sampling import SamplerConfig

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

TINY = resolve_spec("llama-tiny")
GREEDY = SamplerConfig(temperature=0.0)


def _assert_same_or_tie_flip(prompt, a, b, tol=0.05, member_seed=0):
    """Sequences must match token-for-token; the single allowed exception is
    an argmax near-tie: the multi-token verification program reassociates
    float ops differently from the single-token program, so two logits
    within ~1e-3 (bf16 model) can flip order. On the first divergence,
    check against a cache-free full forward that BOTH choices sit within
    ``tol`` of the true max logit — corruption would produce a token far
    below the max — then stop comparing (the sequences legitimately differ
    after a flip). ``member_seed`` selects the weight seed to audit against
    (stacked-members callers pass the member's seed)."""
    if a == b:
        return
    from quorum_tpu.models.init import init_params
    from quorum_tpu.models.transformer import forward_logits

    i = next(i for i, (x, y) in enumerate(zip(a, b)) if x != y)
    params = init_params(TINY, member_seed)
    seq = np.asarray([list(prompt) + a[:i]], np.int32)
    logits = np.asarray(forward_logits(params, TINY, seq)[0, -1], np.float32)
    top = float(logits.max())
    assert top - logits[a[i]] < tol and top - logits[b[i]] < tol, (
        f"divergence at {i} is not a near-tie: max={top:.4f}, "
        f"plain[{a[i]}]={logits[a[i]]:.4f}, spec[{b[i]}]={logits[b[i]]:.4f}")


def test_speculative_matches_plain_greedy():
    """Greedy output with spec_decode=4 must equal the plain engine's
    (up to documented argmax near-ties), for prompts with and without
    self-repetition."""
    plain = InferenceEngine(TINY, decode_chunk=4, n_slots=2)
    spec = InferenceEngine(TINY, decode_chunk=4, n_slots=2, spec_decode=4)
    assert spec.spec_decode == 4
    prompts = [
        [5, 6, 7],
        [9, 8, 9, 8, 9, 8, 9, 8],            # repetitive → drafts accepted
        [(3 + 7 * i) % 500 for i in range(40)],
    ]
    for p in prompts:
        a = plain.generate(p, max_new_tokens=16, sampler=GREEDY).token_ids
        b = spec.generate(p, max_new_tokens=16, sampler=GREEDY).token_ids
        assert len(b) == 16
        _assert_same_or_tie_flip(p, a, b)


def test_draft_lookup_unit():
    """Prompt-lookup drafting: the trailing 2-gram's earlier occurrence is
    continued; the lagged index never matches the trailing pair itself."""
    from quorum_tpu.engine.engine import InferenceEngine, _Request

    req = _Request([1, 2, 3, 9, 1, 2, 3], 8, GREEDY, 0, None, None, None)
    assert InferenceEngine._draft(req, 4) == [9, 1, 2, 3]  # continue from idx 2
    assert InferenceEngine._draft(req, 2) == [9, 1]
    # no earlier occurrence of the trailing pair → no draft
    req2 = _Request([1, 2, 3, 4, 5, 6], 8, GREEDY, 0, None, None, None)
    assert InferenceEngine._draft(req2, 4) is None
    # generated tokens extend the index (lagged): after emitting 9, 1, 2 the
    # pair (1, 2) from the new text is found and its continuation proposed
    eng = InferenceEngine.__new__(InferenceEngine)  # only _emit's index path
    eng.n_tokens = 0
    for t in (9, 1, 2):
        req2.emitted += 1
        req2.hist.append(t)
        if len(req2.hist) >= 3:
            req2.ngram[(req2.hist[-3], req2.hist[-2])] = len(req2.hist) - 2
    assert InferenceEngine._draft(req2, 3) == [3, 4, 5]


def test_verification_accepts_correct_drafts():
    """When drafts ARE the model's continuation (oracle drafting), the
    engine must accept them: the whole generation completes in far fewer
    verify dispatches than tokens (each dispatch advances 1 + accepted)."""
    plain = InferenceEngine(TINY, decode_chunk=1, n_slots=1)
    ref = plain.generate([5, 6, 7], max_new_tokens=24, sampler=GREEDY).token_ids

    eng = InferenceEngine(TINY, decode_chunk=1, n_slots=1, spec_decode=4)
    eng._draft = lambda req, g: (ref[req.emitted : req.emitted + g]
                                 if req.emitted + g <= len(ref) else None)
    calls = {"n": 0}
    real = eng._verify_fn

    def counting(*args, **kwargs):
        fn = real(*args, **kwargs)

        def wrapped(*a, **k):
            calls["n"] += 1
            return fn(*a, **k)
        return wrapped

    eng._verify_fn = counting
    out = eng.generate([5, 6, 7], max_new_tokens=12, sampler=GREEDY).token_ids
    assert len(out) == 12
    assert 0 < calls["n"] <= 4, (
        f"oracle drafts should be accepted (≈3 dispatches for 12 tokens at "
        f"g=4), got {calls['n']}")


def test_sampling_requests_match_plain_engine():
    """Sampled requests SPECULATE too (round-3 extension) — and must still
    produce exactly a spec_decode=0 engine's tokens. (Requests with
    penalties/bias/logprobs are the ones that bypass to the chunked path —
    pinned by the eligibility test below.)"""
    plain = InferenceEngine(TINY, decode_chunk=4, n_slots=2)
    spec = InferenceEngine(TINY, decode_chunk=4, n_slots=2, spec_decode=4)
    sampler = SamplerConfig(temperature=0.8, top_p=0.9)
    a = plain.generate([5, 6, 7], max_new_tokens=12, sampler=sampler,
                       seed=3).token_ids
    b = spec.generate([5, 6, 7], max_new_tokens=12, sampler=sampler,
                      seed=3).token_ids
    assert a == b


def test_speculative_near_context_limit_is_safe():
    """Near max_seq the verify step would write past the cache; the engine
    must fall back to the normal path and still fill the context exactly."""
    import dataclasses

    small = dataclasses.replace(TINY, max_seq=32)
    eng = InferenceEngine(small, decode_chunk=2, n_slots=1, spec_decode=4)
    prompt = [(5 + i) % 500 for i in range(24)]
    out = eng.generate(prompt, max_new_tokens=64, sampler=GREEDY).token_ids
    assert len(out) == 32 - 24  # budget clamped to the window
    plain = InferenceEngine(small, decode_chunk=2, n_slots=1)
    ref = plain.generate(prompt, max_new_tokens=64, sampler=GREEDY).token_ids
    assert len(ref) == len(out)  # both fill the window; tokens may tie-flip


def test_mixed_batch_speculates_only_when_all_eligible():
    """A greedy request co-batched with a sampling request must not flip the
    sampler's stream: results equal the serial runs."""
    from concurrent.futures import ThreadPoolExecutor

    eng = InferenceEngine(TINY, decode_chunk=4, n_slots=2, spec_decode=4)
    jobs = [
        dict(prompt_ids=[5, 6, 7], max_new_tokens=10, sampler=GREEDY, seed=0),
        dict(prompt_ids=[8, 9, 10], max_new_tokens=10,
             sampler=SamplerConfig(temperature=0.9, top_p=0.9), seed=4),
    ]
    serial = [eng.generate(**j).token_ids for j in jobs]
    with ThreadPoolExecutor(max_workers=2) as ex:
        conc = list(ex.map(lambda j: eng.generate(**j).token_ids, jobs))
    assert conc == serial


def test_sampled_requests_match_non_speculative_path():
    """Sampled speculation: verification samples every position with the
    row's own RNG chain (one key split per emitted token), so a sampled
    request through a spec_decode engine emits EXACTLY the tokens the
    non-speculative engine emits for the same seed."""
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = resolve_spec("llama-tiny", {"n_kv_heads": "4", "max_seq": "256"})
    sampler = SamplerConfig(temperature=0.8, top_p=0.9)
    prompt = [3, 4, 5, 3, 4, 5, 3, 4]  # repeats → prompt-lookup drafts fire

    plain = InferenceEngine(spec, decode_chunk=4, n_slots=2)
    refs = [plain.generate(prompt, max_new_tokens=16, sampler=sampler,
                           seed=sd).token_ids for sd in (0, 7, 23)]
    plain.shutdown()

    eng = InferenceEngine(spec, decode_chunk=4, n_slots=2, spec_decode=4)
    outs = [eng.generate(prompt, max_new_tokens=16, sampler=sampler,
                         seed=sd).token_ids for sd in (0, 7, 23)]
    eng.shutdown()
    assert outs == refs, "sampled speculation changed the token stream"
    # (prompt-lookup drafts rarely fire on random-model sampled text — the
    # draft-model test below pins that speculation truly ENGAGES for
    # sampled requests.)


def test_mixed_greedy_and_sampled_cobatch_matches():
    from concurrent.futures import ThreadPoolExecutor

    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = resolve_spec("llama-tiny", {"n_kv_heads": "4", "max_seq": "256"})
    jobs = [([3, 4, 5, 3, 4, 5], SamplerConfig(temperature=0.0), 1),
            ([9, 10, 11, 9, 10], SamplerConfig(temperature=0.9, top_p=0.8), 5)]

    plain = InferenceEngine(spec, decode_chunk=4, n_slots=2)
    refs = [plain.generate(p, max_new_tokens=10, sampler=s, seed=sd).token_ids
            for p, s, sd in jobs]
    plain.shutdown()

    eng = InferenceEngine(spec, decode_chunk=4, n_slots=2, spec_decode=4)
    with ThreadPoolExecutor(max_workers=2) as ex:
        outs = list(ex.map(
            lambda j: eng.generate(j[0], max_new_tokens=10, sampler=j[1],
                                   seed=j[2]).token_ids, jobs))
    eng.shutdown()
    assert outs == refs


def test_sampled_draft_model_composition():
    """Oracle draft model + sampled target: still exact vs non-speculative
    (the draft proposes its greedy chain; acceptance compares against the
    target's SAMPLED chain — fewer accepts at high temperature, identical
    content always)."""
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = resolve_spec("llama-tiny", {"n_kv_heads": "4", "max_seq": "256"})
    sampler = SamplerConfig(temperature=0.5, top_p=0.95)

    plain = InferenceEngine(spec, decode_chunk=4, n_slots=2)
    ref = plain.generate([5, 6, 7, 8], max_new_tokens=12, sampler=sampler,
                         seed=11).token_ids
    plain.shutdown()

    eng = InferenceEngine(spec, decode_chunk=4, n_slots=2, spec_decode=4,
                          draft_spec=spec, draft_seed=0)
    got = eng.generate([5, 6, 7, 8], max_new_tokens=12, sampler=sampler,
                       seed=11).token_ids
    m = eng.metrics()
    eng.shutdown()
    assert got == ref
    assert m["spec_turns_total"] > 0, "speculation never engaged for sampling"


def test_penalty_requests_bypass_speculation():
    """Requests with penalties/bias/logprobs are NOT spec_clean: the verify
    program omits those logit adjustments, so such requests must take the
    chunked path — pinned by exact equality with a spec_decode=0 engine
    (the verify path, which samples unadjusted logits, would diverge)."""
    plain = InferenceEngine(TINY, decode_chunk=4, n_slots=2)
    spec = InferenceEngine(TINY, decode_chunk=4, n_slots=2, spec_decode=4)
    sampler = SamplerConfig(temperature=0.8, top_p=0.9)
    def run(eng):
        req = eng.submit([5, 6, 7, 5, 6, 7], max_new_tokens=12,
                         sampler=sampler, seed=3, frequency_penalty=1.5)
        return list(eng.stream_results(req))

    a = run(plain)
    b = run(spec)
    plain.shutdown()
    spec.shutdown()
    assert a == b
