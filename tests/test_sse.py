"""SSE encode/parse round-trips, including events split across chunk boundaries."""

from quorum_tpu import sse


def test_encode_event_json():
    b = sse.encode_event({"a": 1})
    assert b == b'data: {"a":1}\n\n'


def test_encode_done():
    assert sse.encode_done() == b"data: [DONE]\n\n"


def test_parse_single_event():
    p = sse.SSEParser()
    events = list(p.feed(b'data: {"x": 1}\n\n'))
    assert events == [{"x": 1}]


def test_parse_split_across_chunks():
    p = sse.SSEParser()
    out = []
    for chunk in [b"da", b'ta: {"x"', b": 1}\n", b"\ndata: [D", b"ONE]\n\n"]:
        out.extend(p.feed(chunk))
    assert out == [{"x": 1}, sse.DONE]


def test_parse_crlf_frames():
    p = sse.SSEParser()
    events = list(p.feed(b'data: {"y":2}\r\n\r\ndata: [DONE]\r\n\r\n'))
    assert events == [{"y": 2}, sse.DONE]


def test_parse_multiple_events_one_chunk():
    body = sse.encode_event({"i": 0}) + sse.encode_event({"i": 1}) + sse.encode_done()
    assert list(sse.iter_data_events(body)) == [{"i": 0}, {"i": 1}, sse.DONE]


def test_non_json_data_yielded_raw():
    p = sse.SSEParser()
    assert list(p.feed(b"data: not json\n\n")) == ["not json"]


def test_flush_trailing_event():
    p = sse.SSEParser()
    assert list(p.feed(b'data: {"z":3}')) == []
    assert list(p.flush()) == [{"z": 3}]


def test_ignores_non_data_lines():
    p = sse.SSEParser()
    assert list(p.feed(b"event: ping\nid: 7\n\n")) == []


def test_roundtrip():
    payloads = [{"id": "chatcmpl-parallel-0", "choices": [{"delta": {"content": "hi"}}]}]
    body = b"".join(sse.encode_event(e) for e in payloads) + sse.encode_done()
    assert list(sse.iter_data_events(body)) == payloads + [sse.DONE]
