"""SSE write coalescing: k ready events ship as k frames in ONE socket
flush, and the per-flush trace marks stay correct (ISSUE satellite).

The contract has three parts, each pinned here at its own layer:

  1. The tpu backend's stream marks every event it KNOWS is followed by an
     already-queued successor as ``oai.MoreChunk``.
  2. The server's byte generators buffer marked frames and yield the join —
     one yielded bytes chunk = one ASGI send = one socket flush.
  3. ``sse.instrument_stream`` counts content frames per flush, so
     ``trace.n_tokens`` still counts delivered deltas while ``n_flushes``
     counts actual writes.
"""

import asyncio

from quorum_tpu import oai, sse
from quorum_tpu.observability import RequestTrace


def _chunk(text, more=False, **kw):
    c = oai.chunk(id="chatcmpl-x", model="m", delta={"content": text}, **kw)
    return oai.more(c) if more else c


def _collect(agen):
    async def go():
        return [b async for b in agen]

    return asyncio.run(go())


def test_marked_chunks_join_into_one_flush():
    from quorum_tpu.server.app import _stream_with_role

    async def rest():
        # one decode chunk delivered 3 tokens: first two marked
        yield _chunk("a", more=True)
        yield _chunk("b", more=True)
        yield _chunk("c")
        yield _chunk("d")  # next chunk's lone token: its own flush

    flushes = _collect(_stream_with_role(None, rest(), "m"))
    # role flush + coalesced(a,b,c) + d + [DONE]
    assert len(flushes) == 4
    joined = flushes[1]
    assert joined.count(b"data: ") == 3
    assert b'"content":"a"' in joined and b'"content":"c"' in joined
    assert flushes[2].count(b"data: ") == 1
    assert flushes[-1] == sse.encode_done()
    # every flush is still a valid SSE byte run (parser sees 6 events)
    events = list(sse.iter_data_events(b"".join(flushes)))
    assert len(events) == 6


def test_stream_never_strands_marked_frames():
    """A stream ending on a marked chunk (producer raced the close) must
    still flush it before [DONE]."""
    from quorum_tpu.server.app import _stream_with_role

    async def rest():
        yield _chunk("tail", more=True)

    flushes = _collect(_stream_with_role(None, rest(), "m"))
    assert any(b'"content":"tail"' in f for f in flushes)
    assert flushes[-1] == sse.encode_done()


def test_instrument_stream_counts_frames_per_flush():
    trace = RequestTrace("req-1")

    async def wire():
        yield sse.encode_event(oai.chunk(
            id="x", model="m", delta={"role": "assistant"}))  # no content
        yield (sse.encode_event(_chunk("a")) + sse.encode_event(_chunk("b"))
               + sse.encode_event(_chunk("c")))               # one flush, 3 tokens
        yield sse.encode_event(_chunk("d"))
        yield sse.encode_done()

    _collect(sse.instrument_stream(wire(), trace))
    assert trace.n_flushes == 4
    assert trace.n_tokens == 4          # 3 coalesced + 1 single
    assert trace.ttft is not None
    assert len(trace.token_times) == 4
    # the 3 coalesced tokens hit the wire together
    assert trace.token_times[0] == trace.token_times[1] == trace.token_times[2]


def test_backend_stream_marks_ready_batches():
    """Driving the real TpuBackend.stream over a scripted engine: events
    drained from the queue in one batch carry the MoreChunk marker on all
    but the last."""
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec
    from tests.test_openai_knobs import _MultiScriptEngine

    b = TpuBackend.from_spec(BackendSpec(
        name="co", url="tpu://llama-tiny?seed=2", model="m"))
    b.engine = _MultiScriptEngine([[65, 66, 67, 68]])

    async def go():
        marked, total = 0, 0
        async for ch in b.stream(
            {"model": "m", "messages": [{"role": "user", "content": "q"}],
             "max_tokens": 4, "stream": True}, {}, 60):
            total += 1
            marked += 1 if oai.has_more(ch) else 0
        return marked, total

    marked, total = asyncio.run(go())
    assert total >= 2
    # the final chunk of the stream is never marked (nothing follows it)
    assert marked < total
