"""Streaming SSE contract parity (/root/reference/tests/test_streaming.py):
chunk sequence role→content→stop→[DONE], multi-backend stream shape, all-fail
error chunk, [DONE] guarantee, event ordering."""

import pytest

from quorum_tpu import oai, sse
from quorum_tpu.backends import BackendError, FakeBackend
from tests.conftest import make_client, two_backend_parallel_config

AUTH = {"Authorization": "Bearer sk-test"}


async def collect_events(client, body):
    r = await client.post("/chat/completions", json=body, headers=AUTH)
    assert r.status_code == 200
    assert r.headers["content-type"].startswith("text/event-stream")
    return list(sse.iter_data_events(r.content))


def single_cfg():
    return {
        "settings": {"timeout": 5},
        "primary_backends": [
            {"name": "LLM1", "url": "http://test1.example.com/v1", "model": "m1"}
        ],
    }


class TestSingleBackendStreaming:
    async def test_role_then_content_then_done(self):
        fake = FakeBackend("LLM1", chunks=["Hel", "lo"])
        async with make_client(single_cfg(), LLM1=fake) as client:
            events = await collect_events(client, {"model": "m", "messages": [], "stream": True})
        assert events[-1] == sse.DONE
        # First event: synthetic role chunk
        assert events[0]["id"] == "chatcmpl-role"
        assert events[0]["choices"][0]["delta"] == {"role": "assistant"}
        # Upstream's own role-only chunk was deduplicated
        role_events = [
            e
            for e in events[:-1]
            if e["choices"][0]["delta"].get("role") and not e["choices"][0]["delta"].get("content")
        ]
        assert len(role_events) == 1
        content = "".join(oai.extract_delta_content(e) for e in events[:-1])
        assert content == "Hello"
        finish = [e["choices"][0].get("finish_reason") for e in events[:-1]]
        assert finish[-1] == "stop"

    async def test_backend_error_returns_json_error(self):
        fake = FakeBackend("LLM1", fail_with=BackendError("no stream", status_code=502))
        async with make_client(single_cfg(), LLM1=fake) as client:
            r = await client.post(
                "/chat/completions",
                json={"model": "m", "stream": True},
                headers=AUTH,
            )
        assert r.status_code == 502
        err = r.json()["error"]
        assert err["type"] == "proxy_error"
        assert "Backend failed" in err["message"]

    async def test_mid_stream_failure_emits_error_chunk_and_done(self):
        fake = FakeBackend("LLM1", chunks=["a", "b", "c"], fail_mid_stream=2)
        async with make_client(single_cfg(), LLM1=fake) as client:
            events = await collect_events(client, {"model": "m", "stream": True})
        assert events[-1] == sse.DONE
        error_events = [
            e for e in events[:-1] if e["choices"][0].get("finish_reason") == "error"
        ]
        assert len(error_events) == 1


class TestParallelStreaming:
    async def test_chunk_id_contract(self):
        cfg = two_backend_parallel_config()
        f1 = FakeBackend("LLM1", chunks=["A1", "A2"])
        f2 = FakeBackend("LLM2", chunks=["B1"])
        async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
            events = await collect_events(client, {"model": "m", "stream": True})
        assert events[0]["id"] == "chatcmpl-parallel"
        assert events[0]["choices"][0]["delta"] == {"role": "assistant"}
        ids = {e["id"] for e in events[:-1] if isinstance(e, dict)}
        assert "chatcmpl-parallel-0" in ids
        assert "chatcmpl-parallel-1" in ids
        final = [e for e in events[:-1] if e["id"] == "chatcmpl-parallel-final"]
        assert len(final) == 1
        assert final[0]["choices"][0]["finish_reason"] == "stop"
        assert events[-1] == sse.DONE
        # model name parity
        assert events[0]["model"] == "parallel-proxy"

    async def test_final_chunk_joins_with_separator(self):
        cfg = two_backend_parallel_config(separator="\n===\n")
        f1 = FakeBackend("LLM1", chunks=["Alpha"])
        f2 = FakeBackend("LLM2", chunks=["Beta"])
        async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
            events = await collect_events(client, {"model": "m", "stream": True})
        final = [e for e in events[:-1] if e["id"] == "chatcmpl-parallel-final"][0]
        assert final["choices"][0]["delta"]["content"] == "Alpha\n===\nBeta"

    async def test_skip_final_aggregation(self):
        cfg = two_backend_parallel_config(skip_final_aggregation=True)
        f1 = FakeBackend("LLM1", chunks=["A"])
        f2 = FakeBackend("LLM2", chunks=["B"])
        async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
            events = await collect_events(client, {"model": "m", "stream": True})
        assert not [e for e in events[:-1] if e["id"] == "chatcmpl-parallel-final"]
        assert events[-1] == sse.DONE

    async def test_all_fail_error_chunk(self):
        cfg = two_backend_parallel_config()
        f1 = FakeBackend("LLM1", fail_with=BackendError("x", status_code=500))
        f2 = FakeBackend("LLM2", fail_with=BackendError("y", status_code=500))
        async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
            events = await collect_events(client, {"model": "m", "stream": True})
        error = [e for e in events[:-1] if e.get("id") == "error"]
        assert len(error) == 1
        assert error[0]["choices"][0]["finish_reason"] == "error"
        assert "All backends failed" in error[0]["choices"][0]["delta"]["content"]
        assert events[-1] == sse.DONE

    async def test_partial_failure_serves_survivor(self):
        cfg = two_backend_parallel_config()
        f1 = FakeBackend("LLM1", fail_with=BackendError("dead", status_code=500))
        f2 = FakeBackend("LLM2", chunks=["still here"])
        async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
            events = await collect_events(client, {"model": "m", "stream": True})
        final = [e for e in events[:-1] if e["id"] == "chatcmpl-parallel-final"][0]
        assert final["choices"][0]["delta"]["content"] == "still here"

    async def test_live_interleaving(self):
        """Chunks from a slow and fast backend interleave rather than being
        drained sequentially (fix of reference quirks 1+3)."""
        cfg = two_backend_parallel_config()
        slow = FakeBackend("LLM1", chunks=["s1", "s2", "s3"], chunk_delay=0.03)
        fast = FakeBackend("LLM2", chunks=["f1", "f2", "f3"], chunk_delay=0.001)
        async with make_client(cfg, LLM1=slow, LLM2=fast) as client:
            events = await collect_events(client, {"model": "m", "stream": True})
        order = [
            e["id"]
            for e in events[:-1]
            if isinstance(e, dict) and e["id"].startswith("chatcmpl-parallel-") and e["id"] != "chatcmpl-parallel-final"
        ]
        # fast backend's chunks must all arrive before the slow one's last chunk
        assert order.index("chatcmpl-parallel-1") < len(order) - 1
        first_slow = order.index("chatcmpl-parallel-0")
        last_fast = len(order) - 1 - order[::-1].index("chatcmpl-parallel-1")
        assert last_fast < len(order)  # fast completed
        # interleaving: not all slow chunks come before all fast chunks
        assert order != sorted(order)

    async def test_suppress_individual_responses_request_override(self):
        cfg = two_backend_parallel_config()
        f1 = FakeBackend("LLM1", chunks=["A"])
        f2 = FakeBackend("LLM2", chunks=["B"])
        async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
            events = await collect_events(
                client,
                {"model": "m", "stream": True, "suppress_individual_responses": True},
            )
        per_backend = [
            e
            for e in events[:-1]
            if isinstance(e, dict)
            and e["id"].startswith("chatcmpl-parallel-")
            and e["id"] != "chatcmpl-parallel-final"
        ]
        assert per_backend == []
        final = [e for e in events[:-1] if e["id"] == "chatcmpl-parallel-final"]
        assert len(final) == 1


class TestStreamingThinkFilter:
    async def test_intermediate_think_hidden_and_final_clean(self):
        cfg = two_backend_parallel_config(hide_intermediate_think=True)
        f1 = FakeBackend("LLM1", chunks=["vis<thi", "nk>hidden</think>ible"])
        f2 = FakeBackend("LLM2", chunks=["plain"])
        async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
            events = await collect_events(client, {"model": "m", "stream": True})
        streamed_0 = "".join(
            oai.extract_delta_content(e)
            for e in events[:-1]
            if isinstance(e, dict) and e["id"] == "chatcmpl-parallel-0"
        )
        assert streamed_0 == "visible"
        final = [e for e in events[:-1] if e["id"] == "chatcmpl-parallel-final"][0]
        assert "hidden" not in final["choices"][0]["delta"]["content"]

    async def test_think_preserved_when_disabled(self):
        cfg = two_backend_parallel_config(hide_intermediate_think=False, hide_final_think=False)
        f1 = FakeBackend("LLM1", chunks=["<think>x</think>y"])
        f2 = FakeBackend("LLM2", chunks=["z"])
        async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
            events = await collect_events(client, {"model": "m", "stream": True})
        streamed_0 = "".join(
            oai.extract_delta_content(e)
            for e in events[:-1]
            if isinstance(e, dict) and e["id"] == "chatcmpl-parallel-0"
        )
        assert streamed_0 == "<think>x</think>y"

    async def test_unterminated_think_discarded(self):
        cfg = two_backend_parallel_config(hide_intermediate_think=True)
        f1 = FakeBackend("LLM1", chunks=["ok<think>never closed"])
        f2 = FakeBackend("LLM2", chunks=["fine"])
        async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
            events = await collect_events(client, {"model": "m", "stream": True})
        streamed_0 = "".join(
            oai.extract_delta_content(e)
            for e in events[:-1]
            if isinstance(e, dict) and e["id"] == "chatcmpl-parallel-0"
        )
        assert streamed_0 == "ok"
