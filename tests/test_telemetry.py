"""Engine flight recorder, per-family device-time attribution, SLO
accounting (ISSUE 12, quorum_tpu/telemetry/, docs/observability.md).

Covers the acceptance criteria:
  - a K=4·C=4 run records overlapped in-flight dispatches tagged with
    their compile-budget family, exportable as a Perfetto trace; a
    zero_drain=1 run's admission/injection/register events correlate with
    its decode reaps by request id (and a disagg 1+1 run correlates
    prefill-loop and decode-loop events);
  - every decode program family the engine compiled appears in
    quorum_tpu_dispatch_device_seconds;
  - recorder on vs off produces identical streams, and per-event recorder
    cost stays under a measured per-dispatch budget;
  - the recorder ring is bounded (drop accounting), dumps parse, and the
    dump rate limit holds;
  - SLO classification/scoring/burn-rate, and the /debug/profile
    single-flight 409 + the maybe_profile skip counter.
"""

import json
import os
import time

import pytest

from quorum_tpu import observability as obs
from quorum_tpu.analysis import budget
from quorum_tpu.telemetry.latency import LatencyModel
from quorum_tpu.telemetry.recorder import RECORDER, FlightRecorder
from quorum_tpu.telemetry import slo
from tests.conftest import make_client


# ---- recorder unit ---------------------------------------------------------


def test_recorder_ring_is_bounded_and_counts_drops():
    dropped = []
    rec = FlightRecorder(capacity=32, enabled=True)
    rec.on_drop = lambda: dropped.append(1)
    for i in range(100):
        rec.record("tick", rid=f"r{i}", n=i)
    assert rec.depth() == 32
    assert rec.total() == 100
    assert len(dropped) == 100 - 32
    events = rec.snapshot()
    assert len(events) == 32
    assert events[-1]["n"] == 99  # newest kept, oldest overwritten
    assert events[0]["n"] == 68


def test_recorder_disabled_records_nothing():
    rec = FlightRecorder(capacity=32, enabled=False)
    rec.record("tick")
    assert rec.depth() == 0 and rec.total() == 0
    assert rec.dump("test") is None


def test_recorder_dump_writes_parseable_artifact(tmp_path, monkeypatch):
    monkeypatch.setenv("QUORUM_TPU_FLIGHT_DUMP_INTERVAL", "0.2")
    rec = FlightRecorder(capacity=32, enabled=True)
    rec.record("containment", rid="r1", engine="e1",
               error="FaultInjected: injected fault at 'engine.admit'")
    path = rec.dump("containment", log_dir=str(tmp_path))
    assert path is not None and os.path.exists(path)
    body = json.loads(open(path).read())
    assert body["reason"] == "containment"
    assert any("engine.admit" in json.dumps(e) for e in body["events"])
    # rate limit: an immediate second dump for the same reason is skipped;
    # a different reason is not
    assert rec.dump("containment", log_dir=str(tmp_path)) is None
    assert rec.dump("fail-all", log_dir=str(tmp_path)) is not None


def test_recorder_perfetto_export_shapes():
    rec = FlightRecorder(capacity=64, enabled=True)
    t0 = time.perf_counter()
    rec.record("dispatch", engine="e1", loop="decode", t=t0, seq=1,
               family="loop", depth=0, rids=["r1"])
    rec.record("reap", engine="e1", loop="decode", seq=1, family="loop",
               depth=0, t_issue=t0, t_ready=t0 + 0.25, rids=["r1"])
    rec.record("admit", rid="r1", engine="e1", loop="prefill")
    te = rec.to_trace_events()
    meta = [e for e in te if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in meta)
    xs = [e for e in te if e["ph"] == "X"]
    assert len(xs) == 1
    x = xs[0]
    assert x["name"] == "loop" and x["args"]["rids"] == ["r1"]
    assert abs(x["dur"] - 0.25e6) < 1e3  # microseconds
    instants = [e for e in te if e["ph"] == "i"]
    assert any(e["name"] == "admit" and e["args"]["rid"] == "r1"
               for e in instants)


def test_recorder_overhead_under_per_dispatch_budget():
    """Bounded overhead: the mean cost of one record() must sit far below
    anything a dispatch costs. Budget: 200 microseconds per event — a
    dispatch's host turnaround is measured in the hundreds of
    microseconds at best, so the recorder stays < ~0.1% of a dispatch
    even on a loaded CI core (typical measured cost is ~2 us)."""
    rec = FlightRecorder(capacity=4096, enabled=True)
    n = 5000
    t0 = time.perf_counter()
    for i in range(n):
        rec.record("dispatch", engine="e", loop="decode", seq=i,
                   family="loop", depth=i % 4, rids=["r1", "r2"])
    per_event = (time.perf_counter() - t0) / n
    assert per_event < 200e-6, f"record() cost {per_event * 1e6:.1f}us/event"


# ---- latency model ---------------------------------------------------------


def test_latency_model_ewma_and_percentiles():
    m = LatencyModel(alpha=0.5)
    for v in (0.1, 0.2, 0.3, 0.4):
        m.observe("loop", v)
    m.observe("plain", 0.05)
    assert m.families() == ["loop", "plain"]
    # ewma: 0.1 -> 0.15 -> 0.225 -> 0.3125
    assert abs(m.ewma("loop") - 0.3125) < 1e-9
    assert m.ewma("missing") == 0.0
    snap = m.snapshot()
    assert snap["loop"]["count"] == 4
    # nearest-rank: p50 of 4 samples is the 2nd value, p99 the 4th
    assert snap["loop"]["p50_ms"] == 200.0
    assert snap["loop"]["p99_ms"] == 400.0
    assert snap["plain"]["count"] == 1
    assert snap["plain"]["p50_ms"] == snap["plain"]["p99_ms"] == 50.0


# ---- SLO accounting --------------------------------------------------------


def test_slo_classification_by_deadline_headroom(monkeypatch):
    monkeypatch.setenv("QUORUM_TPU_SLO_INTERACTIVE_S", "30")
    assert slo.classify(5.0) == "interactive"
    assert slo.classify(30.0) == "interactive"
    assert slo.classify(31.0) == "batch"
    assert slo.classify(None) == "batch"


def test_slo_score_trace_and_burn_rate(monkeypatch):
    monkeypatch.setenv("QUORUM_TPU_SLO_TTFT_INTERACTIVE_S", "0.5")
    monkeypatch.setenv("QUORUM_TPU_SLO_GAP_INTERACTIVE_S", "0.1")
    tracker = slo.SloTracker()
    good0 = obs.SLO_GOOD.value
    breach0 = obs.SLO_BREACHED.value

    t = obs.RequestTrace("req-slo-good")
    t.meta["slo"] = "interactive"
    t.ttft = 0.2
    t.max_token_gap = 0.05
    t.status = 200
    tracker.score_trace(t)
    t2 = obs.RequestTrace("req-slo-bad")
    t2.meta["slo"] = "interactive"
    t2.ttft = 2.0                       # breaches ttft
    t2.max_token_gap = 0.5              # breaches inter_token
    t2.status = 504                     # breaches deadline
    tracker.score_trace(t2)

    snap = tracker.snapshot()
    st = snap["interactive"]["stages"]
    assert st["ttft"] == {"good": 1, "breached": 1}
    assert st["inter_token"] == {"good": 1, "breached": 1}
    assert st["deadline"] == {"good": 1, "breached": 1}
    assert snap["interactive"]["burn_rate"] == 0.5
    assert snap["batch"]["stages"] == {}
    # the process-global counters advanced with class/stage labels
    assert obs.SLO_GOOD.value == good0 + 3
    assert obs.SLO_BREACHED.value == breach0 + 3
    assert obs.SLO_GOOD.value_of(**{"class": "interactive",
                                    "stage": "ttft"}) >= 1


def test_slo_untagged_and_client_gone_traces_not_scored():
    tracker = slo.SloTracker()
    t = obs.RequestTrace("req-untagged")
    t.ttft = 0.1
    t.status = 200
    tracker.score_trace(t)             # no meta.slo -> ignored
    gone = obs.RequestTrace("req-gone")
    gone.meta["slo"] = "interactive"
    gone.status = 499                  # client disconnect: no deadline score
    tracker.score_trace(gone)
    assert tracker.snapshot()["interactive"]["stages"].get("deadline") \
        is None


def test_slo_ready_burn_threshold_parsing(monkeypatch):
    monkeypatch.delenv("QUORUM_TPU_SLO_READY_BURN", raising=False)
    assert slo.ready_burn_threshold() is None
    assert slo.burning_class() is None
    monkeypatch.setenv("QUORUM_TPU_SLO_READY_BURN", "0.5")
    assert slo.ready_burn_threshold() == 0.5
    monkeypatch.setenv("QUORUM_TPU_SLO_READY_BURN", "junk")
    assert slo.ready_burn_threshold() is None
    monkeypatch.setenv("QUORUM_TPU_SLO_READY_BURN", "1.5")
    assert slo.ready_burn_threshold() is None


# ---- engine integration ----------------------------------------------------


def _tiny_engine(**kw):
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import MODEL_PRESETS

    return InferenceEngine(MODEL_PRESETS["llama-tiny"], **kw)


def _greedy():
    from quorum_tpu.ops.sampling import SamplerConfig

    return SamplerConfig(temperature=0.0)


def test_megachunk_run_records_family_tagged_overlapped_dispatches():
    """The K=4·C=4 acceptance: dispatch/reap events tagged with the
    "loop" compile-budget family, some dispatched at ring depth > 0
    (overlap), and the Perfetto export renders them as X slices."""
    eng = _tiny_engine(decode_chunk=4, decode_pipeline=4, decode_loop=4)
    # Warm the programs first: the ring only dispatches AHEAD onto warm
    # programs, so overlap is observable from the second generation on.
    eng.generate([5, 6, 7], max_new_tokens=32, sampler=_greedy())
    RECORDER.reset()
    res = eng.generate([5, 6, 7], max_new_tokens=32, sampler=_greedy())
    assert len(res.token_ids) == 32
    events = RECORDER.snapshot()
    mine = [e for e in events if e.get("engine") == eng._tag]
    reaps = [e for e in mine if e["kind"] == "reap"]
    assert reaps, mine
    assert all(e["family"] == "loop" for e in reaps), reaps
    assert all(e["t_ready"] >= e["t_issue"] for e in reaps)
    # dispatch/reap pair by seq
    disp = {e["seq"] for e in mine if e["kind"] == "dispatch"}
    assert {e["seq"] for e in reaps} <= disp
    assert any(e["depth"] > 0 for e in reaps) or eng.n_overlapped > 0
    xs = [e for e in RECORDER.to_trace_events() if e.get("ph") == "X"]
    assert any(e["name"] == "loop" for e in xs)
    # the per-engine latency model saw the same family
    assert "loop" in eng.latency.snapshot()
    assert eng.latency.ewma("loop") > 0.0
    eng.shutdown()


def test_every_compiled_decode_family_appears_in_device_seconds():
    """Acceptance: every family in compile_budget.json that EXECUTES
    appears in quorum_tpu_dispatch_device_seconds — checked as: every
    family classified from this engine's decode program cache has a
    labeled series after traffic (spec engine adds the verify family)."""
    eng = _tiny_engine(decode_chunk=4, decode_pipeline=2, spec_decode=4)
    import numpy as np

    bias = np.zeros((eng.spec.vocab_size,), np.float32)
    bias[7] = 1e9  # forced-periodic stream: prompt-lookup drafting engages
    req = eng.submit([7, 7, 7, 7], max_new_tokens=16, sampler=_greedy(),
                     logit_bias=bias)
    toks = list(eng.stream_results(req))
    assert len(toks) == 16
    assert eng.n_spec_turns > 0
    compiled = budget.decode_families(eng._decode_cache)
    assert "verify" in compiled
    observed = {dict(k).get("family")
                for k in obs.DISPATCH_DEVICE_SECONDS.snapshot()}
    missing = compiled - observed
    assert not missing, (compiled, observed)
    # admission-path families attribute too (single-shot admit here)
    assert "single_shot" in observed
    eng.shutdown()


def test_recorder_on_vs_off_streams_identical():
    """Token-for-token pin: the recorder observes, never steers."""
    prompt, n = [3, 4, 5], 24

    def run_with(enabled):
        old = RECORDER.enabled
        RECORDER.enabled = enabled
        try:
            eng = _tiny_engine(decode_chunk=4, decode_pipeline=4,
                               decode_loop=4, seed=11)
            out = eng.generate(prompt, max_new_tokens=n,
                               sampler=_greedy()).token_ids
            sampled = eng.generate(prompt, max_new_tokens=n,
                                   sampler=_greedy().__class__(
                                       temperature=0.9), seed=7).token_ids
            eng.shutdown()
            return out, sampled
        finally:
            RECORDER.enabled = old

    on = run_with(True)
    off = run_with(False)
    assert on == off


def test_zero_drain_injection_events_correlate_by_rid():
    """The zero_drain=1 acceptance half: staged admission events
    (stage-admit → inject → register) and the decode ring's reaps carry
    the SAME request id, so the injection path is one correlated
    timeline."""
    RECORDER.reset()
    eng = _tiny_engine(decode_chunk=4, decode_pipeline=4, decode_loop=2,
                       n_slots=2, prefill_chunk=16, zero_drain=True)
    prompt = [(7 + 3 * i) % eng.spec.vocab_size for i in range(40)]
    res = eng.generate(prompt, max_new_tokens=8, sampler=_greedy())
    assert len(res.token_ids) == 8
    events = [e for e in RECORDER.snapshot()
              if e.get("engine") == eng._tag]
    by_kind = {}
    for e in events:
        by_kind.setdefault(e["kind"], []).append(e)
    assert by_kind.get("stage-admit"), by_kind.keys()
    assert by_kind.get("inject"), by_kind.keys()
    assert by_kind.get("register"), by_kind.keys()
    rid = by_kind["stage-admit"][0]["rid"]
    assert any(e["rid"] == rid for e in by_kind["inject"])
    assert any(e["rid"] == rid for e in by_kind["register"])
    assert any(rid in e.get("rids", ()) for e in by_kind.get("reap", []))
    eng.shutdown()


def test_disagg_prefill_and_decode_loop_events_correlate_by_rid():
    """Dual-loop correlation: under disagg the admit/handoff events come
    from the prefill loop and the register/reap from the decode loop —
    one request id ties them together across threads."""
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.parallel.mesh import disagg_meshes
    from quorum_tpu.engine.engine import InferenceEngine

    RECORDER.reset()
    pm, dm = disagg_meshes(1, 1)
    tiny = resolve_spec("llama-tiny", {"n_kv_heads": "4"})
    eng = InferenceEngine(tiny, dm, prefill_mesh=pm, decode_chunk=4,
                          n_slots=2, prefill_chunk=16, seed=3)
    res = eng.generate([3, 4, 5], max_new_tokens=6, sampler=_greedy())
    assert len(res.token_ids) == 6
    events = [e for e in RECORDER.snapshot()
              if e.get("engine") == eng._tag]
    handoffs = [e for e in events if e["kind"] == "handoff"]
    registers = [e for e in events if e["kind"] == "register"]
    assert handoffs and registers
    assert all(e["loop"] == "prefill" for e in handoffs)
    assert all(e["loop"] == "decode" for e in registers)
    rid = handoffs[0]["rid"]
    assert any(e["rid"] == rid for e in registers)
    reaps = [e for e in events if e["kind"] == "reap"]
    assert any(rid in e.get("rids", ()) for e in reaps)
    eng.shutdown()


# ---- server endpoints ------------------------------------------------------


def _config():
    return {
        "settings": {"timeout": 60},
        "primary_backends": [
            {"name": "T", "url": "tpu://llama-tiny?seed=3&slots=2",
             "model": "t"},
        ],
    }


async def test_timeline_endpoint_json_and_perfetto():
    async with make_client(_config()) as client:
        r = await client.post(
            "/chat/completions",
            json={"model": "t", "max_tokens": 4,
                  "messages": [{"role": "user", "content": "hi"}]},
            headers={"Authorization": "Bearer x"})
        assert r.status_code == 200
        body = (await client.get("/debug/engine/timeline")).json()
        assert body["clock"] == "perf_counter"
        assert any(e["kind"] == "reap" for e in body["events"])
        # per-engine per-family device-time stats ride the JSON form
        assert "T" in body["device_time"]
        assert body["device_time"]["T"], body["device_time"]
        assert set(body["slo"]) == {"interactive", "batch"}
        perf = (await client.get(
            "/v1/debug/engine/timeline?format=perfetto")).json()
        assert any(e.get("ph") == "X" for e in perf["traceEvents"])
        bad = await client.get("/debug/engine/timeline?format=nope")
        assert bad.status_code == 400


async def test_profile_endpoint_single_flight_409():
    async with make_client(_config()) as client:
        skipped0 = obs.PROFILE_SKIPPED.value
        assert obs._profile_lock.acquire(blocking=False)
        try:
            busy = await client.post("/debug/profile?seconds=0.01")
        finally:
            obs._profile_lock.release()
        assert busy.status_code == 409
        assert busy.json()["error"]["type"] == "conflict_error"
        assert "retry-after" in {k.lower() for k in busy.headers}
        assert obs.PROFILE_SKIPPED.value == skipped0 + 1
        bad = await client.post("/debug/profile?seconds=oops")
        assert bad.status_code == 400


def test_maybe_profile_skip_is_visible(monkeypatch, tmp_path):
    """The PR's satellite fix: a concurrent-profile skip used to be a
    silent DEBUG line; now it ticks the counter and records an event."""
    monkeypatch.setenv("QUORUM_TPU_PROFILE_DIR", str(tmp_path))
    RECORDER.reset()
    skipped0 = obs.PROFILE_SKIPPED.value
    assert obs._profile_lock.acquire(blocking=False)
    try:
        with obs.maybe_profile("req-skip-test"):
            pass
    finally:
        obs._profile_lock.release()
    assert obs.PROFILE_SKIPPED.value == skipped0 + 1
    assert any(e["kind"] == "profile-skipped"
               and e.get("rid") == "req-skip-test"
               for e in RECORDER.snapshot())


def test_health_carries_slo_block_and_burn_shedding(monkeypatch):
    # burning_class flips /health to degraded and /ready to 503 only when
    # the opt-in threshold is set AND a class is burning. A FRESH tracker
    # is swapped in: the process-global one accumulates scores from every
    # other suite test's requests, which would dilute the burn rate.
    monkeypatch.setenv("QUORUM_TPU_SLO_READY_BURN", "0.5")
    tracker = slo.SloTracker()
    monkeypatch.setattr(slo, "SLO", tracker)
    assert slo.burning_class() is None
    for _ in range(4):
        tracker.record("interactive", "ttft", False)
    assert slo.burning_class() == "interactive"
    tracker.reset()
    assert slo.burning_class() is None


async def test_health_slo_block_present_with_engine_backend():
    async with make_client(_config()) as client:
        body = (await client.get("/health")).json()
        assert "slo" in body
        assert set(body["slo"]) == {"interactive", "batch"}


@pytest.mark.slow
async def test_slo_counters_score_served_requests():
    """End to end: a served chat request is classified from its timeout
    headroom and scored at teardown."""
    async with make_client(_config()) as client:
        good0 = obs.SLO_GOOD.value_of(**{"class": "interactive",
                                         "stage": "deadline"})
        r = await client.post(
            "/chat/completions",
            json={"model": "t", "max_tokens": 4, "timeout": 20,
                  "messages": [{"role": "user", "content": "hi"}]},
            headers={"Authorization": "Bearer x"})
        assert r.status_code == 200
        assert obs.SLO_GOOD.value_of(**{"class": "interactive",
                                        "stage": "deadline"}) == good0 + 1
