"""TpuBackend tests: Backend-protocol conformance and end-to-end serving
through the ASGI app with a real (tiny) in-process model."""

import asyncio
import json

import pytest

from tests.conftest import make_client

from quorum_tpu.backends.tpu_backend import TpuBackend, _StopMatcher
from quorum_tpu.config import BackendSpec

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def tiny_backend(name="TPU1", seed=0, model=""):
    return TpuBackend.from_spec(
        BackendSpec(
            name=name,
            url=f"tpu://llama-tiny?seed={seed}&max_tokens=8&decode_chunk=4",
            model=model,
        )
    )


# ---- stop matcher ---------------------------------------------------------

def test_stop_matcher_boundary_split():
    m = _StopMatcher(["END"])
    assert m.feed("abcE") == "abc"     # "E" withheld (possible stop prefix)
    assert m.feed("ND junk") == ""     # stop completes → everything after dropped
    assert m.hit


def test_stop_matcher_false_alarm():
    m = _StopMatcher(["END"])
    assert m.feed("abcE") == "abc"
    assert m.feed("xyz") == "Exyz"     # withheld prefix released
    assert m.flush() == ""


def test_stop_matcher_no_stops_passthrough():
    m = _StopMatcher([])
    assert m.feed("anything") == "anything"


def test_stop_matcher_earliest_occurrence_wins():
    m = _StopMatcher(["world", "hello"])
    assert m.feed("say hello world") == "say "
    assert m.hit


# ---- protocol conformance -------------------------------------------------

async def test_complete_returns_tagged_openai_body():
    b = tiny_backend()
    res = await b.complete({"messages": [{"role": "user", "content": "hi"}]}, {}, 30.0)
    assert res.ok
    assert res.body["backend"] == "TPU1"
    assert res.body["object"] == "chat.completion"
    assert res.body["model"] == "llama-tiny"
    u = res.body["usage"]
    assert u["prompt_tokens"] > 0
    assert u["completion_tokens"] > 0
    assert u["total_tokens"] == u["prompt_tokens"] + u["completion_tokens"]


async def test_complete_model_override_precedence():
    b = tiny_backend(model="my-override")
    res = await b.complete(
        {"model": "req-model", "messages": [{"role": "user", "content": "x"}]}, {}, 30.0
    )
    assert res.body["model"] == "my-override"


async def test_max_tokens_respected():
    b = tiny_backend()
    res = await b.complete(
        {"messages": [{"role": "user", "content": "x"}], "max_tokens": 3}, {}, 30.0
    )
    assert res.body["usage"]["completion_tokens"] <= 3


async def test_deterministic_at_temperature_zero():
    b = tiny_backend()
    body = {"messages": [{"role": "user", "content": "x"}], "temperature": 0}
    r1 = await b.complete(body, {}, 30.0)
    r2 = await b.complete(body, {}, 30.0)
    assert r1.content == r2.content


async def test_stream_chunks_concatenate_to_complete():
    b = tiny_backend()
    body = {"messages": [{"role": "user", "content": "x"}], "temperature": 0}
    full = (await b.complete(body, {}, 30.0)).content
    pieces, finish = [], None
    async for ch in b.stream(dict(body), {}, 30.0):
        d = ch["choices"][0]["delta"]
        if "content" in d and d["content"]:
            pieces.append(d["content"])
        if ch["choices"][0]["finish_reason"]:
            finish = ch["choices"][0]["finish_reason"]
    assert "".join(pieces) == full
    assert finish in ("stop", "length")


async def test_stream_first_chunk_is_role():
    b = tiny_backend()
    chunks = [c async for c in b.stream({"messages": [{"role": "user", "content": "x"}]}, {}, 30.0)]
    assert chunks[0]["choices"][0]["delta"] == {"role": "assistant"}


async def test_stop_sequence_truncates_completion():
    b = tiny_backend()
    body = {"messages": [{"role": "user", "content": "x"}], "temperature": 0}
    full = (await b.complete(body, {}, 30.0)).content
    if len(full) < 2:
        pytest.skip("tiny model generated too little text to split a stop from")
    stop = full[1:3]
    res = await b.complete({**body, "stop": stop}, {}, 30.0)
    assert res.content == full[: full.index(stop)]
    assert res.body["choices"][0]["finish_reason"] == "stop"


def test_sampler_quantization_bounds_programs():
    from quorum_tpu.backends.tpu_backend import _request_sampler

    a = _request_sampler({"temperature": 0.70123})
    b = _request_sampler({"temperature": 0.70456})
    assert a == b  # quantized to the same compiled program


async def test_stream_timeout_aborts_quickly():
    import time

    b = tiny_backend()
    body = {"messages": [{"role": "user", "content": "x"}], "max_tokens": 64}
    t0 = time.monotonic()
    from quorum_tpu.backends.base import BackendError

    with pytest.raises(BackendError):
        async for _ in b.stream(body, {}, 0.000001):
            await asyncio.sleep(0)  # consume until the timeout fires
    # generation (64 tokens) must NOT run to completion after the timeout:
    # the cancel event aborts within one decode chunk.
    assert time.monotonic() - t0 < 20


async def test_engines_shared_across_backends():
    a = tiny_backend("A")
    b = tiny_backend("B")
    c = tiny_backend("C", seed=7)
    assert a.engine is b.engine           # same spec+seed → shared weights
    assert a.engine is not c.engine       # different seed → distinct member


# ---- end-to-end through the server ---------------------------------------

def tpu_parallel_config():
    return {
        "settings": {"timeout": 60},
        "primary_backends": [
            {"name": "M0", "url": "tpu://llama-tiny?seed=0&max_tokens=6", "model": ""},
            {"name": "M1", "url": "tpu://llama-tiny?seed=1&max_tokens=6", "model": ""},
        ],
        "iterations": {"aggregation": {"strategy": "concatenate"}},
        "strategy": {
            "concatenate": {"separator": "\n---\n", "thinking_tags": ["think"]},
            "aggregate": {"source_backends": "all", "aggregator_backend": ""},
        },
    }


async def test_e2e_non_streaming_parallel_tpu():
    async with make_client(tpu_parallel_config()) as client:
        r = await client.post(
            "/chat/completions",
            json={"messages": [{"role": "user", "content": "hi"}], "temperature": 0},
            headers={"Authorization": "Bearer k"},
        )
    assert r.status_code == 200
    body = r.json()
    content = body["choices"][0]["message"]["content"]
    assert "\n---\n" in content   # two members concatenated
    assert body["usage"]["total_tokens"] > 0


async def test_e2e_streaming_parallel_tpu():
    async with make_client(tpu_parallel_config()) as client:
        async with client.stream(
            "POST",
            "/chat/completions",
            json={
                "messages": [{"role": "user", "content": "hi"}],
                "stream": True,
                "temperature": 0,
            },
            headers={"Authorization": "Bearer k"},
        ) as r:
            assert r.status_code == 200
            events = []
            async for line in r.aiter_lines():
                if line.startswith("data: "):
                    events.append(line[6:])
    assert events[-1] == "[DONE]"
    parsed = [json.loads(e) for e in events[:-1]]
    ids = {p["id"] for p in parsed}
    assert any(i.startswith("chatcmpl-parallel-") for i in ids)
    final = [p for p in parsed if p["id"] == "chatcmpl-parallel-final"]
    assert final and final[0]["choices"][0]["finish_reason"] == "stop"


# ---- request validation / usage reporting ---------------------------------

async def test_bad_temperature_is_400_not_500():
    from quorum_tpu.backends.base import BackendError

    b = tiny_backend()
    with pytest.raises(BackendError) as ei:
        await b.complete(
            {"messages": [{"role": "user", "content": "hi"}], "temperature": "abc"},
            {}, 30.0,
        )
    assert ei.value.status_code == 400
    assert ei.value.body["error"]["type"] == "invalid_request_error"


async def test_stream_include_usage_appends_usage_chunk():
    b = tiny_backend()
    chunks = []
    async for c in b.stream(
        {
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 5,
            "stream_options": {"include_usage": True},
        },
        {}, 30.0,
    ):
        chunks.append(c)
    last = chunks[-1]
    assert last["choices"] == []
    assert last["usage"]["completion_tokens"] >= 1
    assert last["usage"]["total_tokens"] == (
        last["usage"]["prompt_tokens"] + last["usage"]["completion_tokens"]
    )
    # the finish_reason chunk still precedes it
    assert chunks[-2]["choices"][0]["finish_reason"] in ("stop", "length")


def test_first_user_message_skips_null_content():
    from quorum_tpu import oai

    body = {
        "messages": [
            {"role": "user", "content": None},
            {"role": "user", "content": "real question"},
        ]
    }
    assert oai.first_user_message(body) == "real question"


async def test_engines_shared_despite_decode_chunk_difference():
    """decode_chunk is a dispatch knob, not weight identity: two backends that
    differ only in decode_chunk share one engine (one copy of weights)."""
    a = TpuBackend.from_spec(
        BackendSpec(name="A", url="tpu://llama-tiny?seed=7&decode_chunk=2")
    )
    b = TpuBackend.from_spec(
        BackendSpec(name="B", url="tpu://llama-tiny?seed=7&decode_chunk=8")
    )
    assert a.engine is b.engine
    assert a.decode_chunk == 2 and b.decode_chunk == 8


# ---- ADVICE round-1 regressions ------------------------------------------

class _ScriptedEngine:
    """Stub engine: yields a fixed token script (ids into a 512-vocab byte
    tokenizer). Lets tests stage exact detokenizer/stop-matcher interactions
    that a real model can't produce deterministically."""

    def __init__(self, tokens, delay=0.0):
        from quorum_tpu.models.model_config import MODEL_PRESETS

        self.spec = MODEL_PRESETS["llama-tiny"]
        self._tokens = list(tokens)
        self._delay = delay

    def generate_stream(self, prompt_ids, *, cancel=None, **kw):
        import time as _time

        for t in self._tokens:
            if cancel is not None and cancel.is_set():
                return
            if self._delay:
                _time.sleep(self._delay)
            yield t

    # New engine API (submit-then-stream, so backends can 503 a full queue
    # before the first SSE byte): the stub has no queue, so submit just
    # captures the args and stream_results replays the script.
    def submit(self, prompt_ids, *, cancel=None, **kw):
        return (prompt_ids, cancel)

    def stream_results(self, req):
        prompt_ids, cancel = req
        yield from self.generate_stream(prompt_ids, cancel=cancel)


def _byte_token(b: int) -> int:
    return 3 + b  # ByteTokenizer: id = _OFFSET + byte


async def test_stop_hit_in_flushed_tail_sets_finish_reason_stop():
    """A stop string that only completes in the detokenizer's flush() tail
    (dangling partial UTF-8 -> replacement char) must still report
    finish_reason="stop" — in both complete() and stream()."""
    # "X" then the first byte of a 2-byte UTF-8 char: flush() emits "X" + U+FFFD
    tokens = [_byte_token(ord("X")), _byte_token(0xC3)]
    body = {
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 8,
        "stop": ["X�"],
    }

    b = TpuBackend("S", _ScriptedEngine(tokens), model="m")
    res = await b.complete(body, {}, 30.0)
    assert res.body["choices"][0]["finish_reason"] == "stop"
    assert res.body["choices"][0]["message"]["content"] == ""

    b2 = TpuBackend("S2", _ScriptedEngine(tokens), model="m")
    finish = None
    async for chunk in b2.stream(body, {}, 30.0):
        for choice in chunk.get("choices", []):
            if choice.get("finish_reason"):
                finish = choice["finish_reason"]
    assert finish == "stop"


async def test_stream_timeout_is_end_to_end_not_per_delta():
    """A generation that keeps emitting deltas must still be bounded by the
    configured timeout (complete() parity), not granted a fresh timeout per
    delta."""
    import time

    from quorum_tpu.backends.base import BackendError

    # 200 tokens, 20ms apart: per-delta waits always succeed, but the
    # end-to-end deadline (0.5s) must fire long before the ~4s total.
    tokens = [_byte_token(ord("a"))] * 200
    b = TpuBackend("T", _ScriptedEngine(tokens, delay=0.02), model="m")
    body = {"messages": [{"role": "user", "content": "x"}], "max_tokens": 200}
    t0 = time.monotonic()
    with pytest.raises(BackendError):
        async for _ in b.stream(body, {}, 0.5):
            pass
    assert time.monotonic() - t0 < 3.0
