"""Request-scoped tracing: /debug/traces span structure for streamed
multi-backend requests, wire-level TTFT / per-token timings, and the
trace-store ring-buffer bound (ISSUE 1 tentpole)."""

import pytest

from tests.conftest import make_client


def _two_tpu_config():
    return {
        "settings": {"timeout": 60},
        "primary_backends": [
            {"name": "LLM1", "url": "tpu://llama-tiny?seed=1&slots=2",
             "model": "t"},
            {"name": "LLM2", "url": "tpu://llama-tiny?seed=2&slots=2",
             "model": "t"},
        ],
        "iterations": {"aggregation": {"strategy": "concatenate"}},
        "strategy": {
            "concatenate": {"separator": "\n---\n"},
            "aggregate": {"source_backends": "all",
                          "aggregator_backend": ""},
        },
    }


async def test_streamed_multibackend_trace_spans():
    """A completed streaming parallel request exposes ordered spans —
    queue-wait, prefill, decode, aggregate, sse-flush — with TTFT and
    per-token wire timings populated (the ISSUE 1 acceptance shape)."""
    async with make_client(_two_tpu_config()) as client:
        resp = await client.post(
            "/v1/chat/completions",
            json={"model": "t", "stream": True, "max_tokens": 6,
                  "messages": [{"role": "user", "content": "hi"}]},
            headers={"Authorization": "Bearer x"},
        )
        assert resp.status_code == 200
        rid = resp.headers["x-request-id"]
        assert "data: [DONE]" in resp.text

        got = await client.get(f"/debug/traces/{rid}")
        assert got.status_code == 200
        trace = got.json()
    assert trace["request_id"] == rid
    assert trace["in_flight"] is False
    assert trace["status"] == 200
    assert trace["duration_ms"] > 0

    names = [s["name"] for s in trace["spans"]]
    for required in ("queue-wait", "prefill", "decode", "aggregate",
                     "sse-flush"):
        assert required in names, f"missing span {required} in {names}"
    # Both backends' engine paths were traced (fan-out = 2 submissions).
    assert names.count("queue-wait") == 2
    assert names.count("prefill") == 2
    assert names.count("fanout-stream") == 2

    # Ordered by start time, every span closed, durations consistent.
    starts = [s["start_s"] for s in trace["spans"]]
    assert starts == sorted(starts)
    for s in trace["spans"]:
        assert s["end_s"] is not None and s["end_s"] >= s["start_s"]

    # Span tags: the fan-out hops carry backend names; decode spans carry
    # step counts and batch occupancy (the step-loop visibility this PR adds).
    fanout_backends = {s["meta"]["backend"] for s in trace["spans"]
                      if s["name"] == "fanout-stream"}
    assert fanout_backends == {"LLM1", "LLM2"}
    decode = next(s for s in trace["spans"] if s["name"] == "decode")
    assert decode["meta"]["steps"] >= 1
    assert decode["meta"]["occupancy"] >= 1

    # Wire-level timings: TTFT set, one entry per content flush, monotone.
    assert trace["ttft_ms"] is not None and trace["ttft_ms"] > 0
    assert trace["tokens"] >= 1
    times = trace["token_times_ms"]
    assert len(times) == trace["tokens"]
    assert times == sorted(times)
    assert times[0] == trace["ttft_ms"]


async def test_trace_listing_and_miss():
    async with make_client(_two_tpu_config()) as client:
        resp = await client.post(
            "/chat/completions",
            json={"model": "t", "max_tokens": 4,
                  "messages": [{"role": "user", "content": "yo"}]},
            headers={"Authorization": "Bearer x"},
        )
        assert resp.status_code == 200
        rid = resp.headers["x-request-id"]

        listing = (await client.get("/debug/traces")).json()
        assert listing["in_flight"] == 0
        assert listing["completed"] >= 1
        rows = {t["request_id"]: t for t in listing["traces"]}
        assert rid in rows
        # summaries stay light: spans/token arrays only on the detail view
        assert "spans" not in rows[rid]
        assert rows[rid]["status"] == 200

        # non-streaming parallel requests trace the fanout + aggregate hops
        detail = (await client.get(f"/v1/debug/traces/{rid}")).json()
        names = [s["name"] for s in detail["spans"]]
        assert "fanout" in names and "aggregate" in names
        assert "queue-wait" in names and "prefill" in names

        missing = await client.get("/debug/traces/req-does-not-exist")
        assert missing.status_code == 404
        assert missing.json()["error"]["type"] == "invalid_request_error"


def test_trace_store_ring_bound():
    from quorum_tpu.observability import RequestTrace, TraceStore

    store = TraceStore(capacity=4)
    for i in range(10):
        t = RequestTrace(f"req-{i}")
        store.start(t)
        t.finish(status=200)
        store.complete(t)
    snap = store.snapshot()
    assert snap["completed"] == 4
    assert [t["request_id"] for t in snap["traces"]] == [
        "req-9", "req-8", "req-7", "req-6"]  # newest first
    assert store.get("req-0") is None  # aged out
    assert store.get("req-9") is not None


def test_trace_span_cap():
    from quorum_tpu.observability import MAX_SPANS, RequestTrace

    t = RequestTrace("req-cap")
    for i in range(MAX_SPANS + 25):
        t.add_span("decode", 0.0, 0.001)
    t.finish(status=200)
    d = t.to_dict()
    assert len(d["spans"]) == MAX_SPANS
    assert d["dropped_spans"] == 25


def test_token_times_cap_keeps_counting():
    """Past MAX_TOKEN_TIMES the stored wire timings stop growing but the
    token count keeps counting every content flush (and inter-token gaps
    keep measuring one flush, not the distance back to the cap entry)."""
    from quorum_tpu.observability import MAX_TOKEN_TIMES, RequestTrace

    t = RequestTrace("req-flood")
    for _ in range(MAX_TOKEN_TIMES + 10):
        t.mark_flush(True)
    t.finish(status=200)
    d = t.to_dict()
    assert len(d["token_times_ms"]) == MAX_TOKEN_TIMES
    assert d["tokens"] == MAX_TOKEN_TIMES + 10


async def test_param_route_method_mismatch_is_405():
    """POST to a /{param} route must 405 like any other known path, not
    404 (the exact-route table's behavior)."""
    async with make_client(_two_tpu_config()) as client:
        resp = await client.post("/debug/traces/req-whatever", json={})
        assert resp.status_code == 405


def test_long_generation_coalesces_decode_spans():
    """A multi-thousand-token generation must not flood the span budget
    with per-chunk decode entries: past the engine's TURN_SPAN_CAP the
    last decode span extends instead (summing steps, counting turns), so
    end-of-stream spans (aggregate, sse-flush) always have room."""
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.observability import RequestTrace, use_trace

    eng = InferenceEngine(resolve_spec("llama-tiny", {"max_seq": "1024"}),
                          decode_chunk=2, n_slots=1)
    trace = RequestTrace("req-long")
    with use_trace(trace):
        req = eng.submit([5, 6, 7], max_new_tokens=200)
    tokens = list(eng.stream_results(req))
    assert len(tokens) == 200
    decode_spans = [s for s in trace.spans if s.name == "decode"]
    assert 1 <= len(decode_spans) <= eng.TURN_SPAN_CAP
    # every chunk's steps are accounted for, appended or coalesced
    total_steps = sum(s.meta.get("steps", 0) for s in decode_spans)
    assert total_steps >= 200 - 1  # first token comes from the admit
    if len(decode_spans) == eng.TURN_SPAN_CAP:
        assert decode_spans[-1].meta.get("coalesced_turns", 0) >= 1
    eng.shutdown()


def test_phase_timer_alias_kept():
    """PhaseTimer is the round-1 name for RequestTrace — old call sites
    (timer.phase / .phases / .total / .log) must keep working."""
    from quorum_tpu.observability import PhaseTimer, RequestTrace

    assert PhaseTimer is RequestTrace
    t = PhaseTimer("req-compat")
    with t.phase("fanout"):
        pass
    assert "fanout" in t.phases
    t.log("complete", status=200)  # must not raise


@pytest.mark.parametrize("path", ["/debug/traces", "/v1/debug/traces"])
async def test_debug_traces_served_on_both_prefixes(path):
    async with make_client(_two_tpu_config()) as client:
        resp = await client.get(path)
        assert resp.status_code == 200
        assert set(resp.json()) == {"capacity", "in_flight", "completed",
                                    "traces"}
