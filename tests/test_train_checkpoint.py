"""Training checkpoint/resume on the virtual mesh (SURVEY §5.4).

Save a sharded TrainState mid-training, restore it (same and different mesh
shape), and verify training continues bit-for-bit; serve from the restored
params through the engine.
"""

import numpy as np
import pytest

import jax

from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.parallel import MeshConfig, make_mesh
from quorum_tpu.training.checkpoint import (
    restore_checkpoint,
    restore_params,
    save_checkpoint,
)
from quorum_tpu.training.trainer import make_train_step, train_init

SPEC = resolve_spec("llama-tiny", {"max_seq": "64"})


def _tokens(seed, batch=4, seqlen=32):
    rng = np.random.RandomState(seed)
    return rng.randint(1, SPEC.vocab_size, size=(batch, seqlen))


def _leaves_equal(a, b):
    fa = jax.tree.leaves(a)
    fb = jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip_and_resume(tmp_path):
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    step = make_train_step(SPEC, mesh)
    state = train_init(SPEC, mesh, seed=0)
    for i in range(3):
        state, _ = step(state, _tokens(i))

    save_checkpoint(str(tmp_path / "ckpt"), state)

    # Continue the original for two more steps → reference trajectory.
    ref = state
    losses_ref = []
    for i in range(3, 5):
        ref, loss = step(ref, _tokens(i))
        losses_ref.append(float(loss))

    # Restore and continue identically.
    restored = restore_checkpoint(str(tmp_path / "ckpt"), SPEC, mesh)
    assert int(restored.step) == 3
    losses_res = []
    for i in range(3, 5):
        restored, loss = step(restored, _tokens(i))
        losses_res.append(float(loss))
    assert losses_res == losses_ref
    _leaves_equal(restored.params, ref.params)


def test_restore_onto_different_mesh_shape(tmp_path):
    mesh_a = make_mesh(MeshConfig(dp=2, tp=4))
    state = train_init(SPEC, mesh_a, seed=1)
    step_a = make_train_step(SPEC, mesh_a)
    state, _ = step_a(state, _tokens(0))
    save_checkpoint(str(tmp_path / "ckpt"), state)

    # Resume on a tp8 mesh: weights re-lay onto the new sharding.
    mesh_b = make_mesh(MeshConfig(tp=8))
    restored = restore_checkpoint(str(tmp_path / "ckpt"), SPEC, mesh_b)
    _leaves_equal(restored.params, state.params)
    step_b = make_train_step(SPEC, mesh_b)
    restored, loss = step_b(restored, _tokens(1))
    assert np.isfinite(float(loss))


def test_serve_from_training_checkpoint(tmp_path):
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.ops.sampling import SamplerConfig

    mesh = make_mesh(MeshConfig(tp=2))
    state = train_init(SPEC, mesh, seed=2)
    save_checkpoint(str(tmp_path / "ckpt"), state)

    params = restore_params(str(tmp_path / "ckpt"), SPEC, mesh)
    eng = InferenceEngine(SPEC, mesh, params=jax.tree.map(np.asarray, params))
    out = eng.generate([5, 6, 7], max_new_tokens=6,
                       sampler=SamplerConfig(temperature=0.0))
    assert len(out.token_ids) == 6
    # and it really is the trained weights: logits match the state's params
    from quorum_tpu.models.transformer import forward_logits

    import jax.numpy as jnp

    toks = jnp.asarray([[5, 6, 7]], jnp.int32)
    a = np.asarray(forward_logits(state.params, SPEC, toks), np.float32)
    b = np.asarray(forward_logits(eng.params, SPEC, toks), np.float32)
    np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2)
