"""Training checkpoint/resume on the virtual mesh (SURVEY §5.4).

Save a sharded TrainState mid-training, restore it (same and different mesh
shape), and verify training continues bit-for-bit; serve from the restored
params through the engine.
"""

import numpy as np
import pytest

import jax

from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.parallel import MeshConfig, make_mesh
from quorum_tpu.training.checkpoint import (
    restore_checkpoint,
    restore_params,
    save_checkpoint,
)
from quorum_tpu.training.trainer import make_train_step, train_init

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

SPEC = resolve_spec("llama-tiny", {"max_seq": "64"})


def _tokens(seed, batch=4, seqlen=32):
    rng = np.random.RandomState(seed)
    return rng.randint(1, SPEC.vocab_size, size=(batch, seqlen))


def _leaves_equal(a, b):
    fa = jax.tree.leaves(a)
    fb = jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip_and_resume(tmp_path):
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    step = make_train_step(SPEC, mesh)
    state = train_init(SPEC, mesh, seed=0)
    for i in range(3):
        state, _ = step(state, _tokens(i))

    save_checkpoint(str(tmp_path / "ckpt"), state)

    # Continue the original for two more steps → reference trajectory.
    ref = state
    losses_ref = []
    for i in range(3, 5):
        ref, loss = step(ref, _tokens(i))
        losses_ref.append(float(loss))

    # Restore and continue identically.
    restored = restore_checkpoint(str(tmp_path / "ckpt"), SPEC, mesh)
    assert int(restored.step) == 3
    losses_res = []
    for i in range(3, 5):
        restored, loss = step(restored, _tokens(i))
        losses_res.append(float(loss))
    assert losses_res == losses_ref
    _leaves_equal(restored.params, ref.params)


def test_restore_onto_different_mesh_shape(tmp_path):
    mesh_a = make_mesh(MeshConfig(dp=2, tp=4))
    state = train_init(SPEC, mesh_a, seed=1)
    step_a = make_train_step(SPEC, mesh_a)
    state, _ = step_a(state, _tokens(0))
    save_checkpoint(str(tmp_path / "ckpt"), state)

    # Resume on a tp8 mesh: weights re-lay onto the new sharding.
    mesh_b = make_mesh(MeshConfig(tp=8))
    restored = restore_checkpoint(str(tmp_path / "ckpt"), SPEC, mesh_b)
    _leaves_equal(restored.params, state.params)
    step_b = make_train_step(SPEC, mesh_b)
    restored, loss = step_b(restored, _tokens(1))
    assert np.isfinite(float(loss))


def test_serve_from_training_checkpoint(tmp_path):
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.ops.sampling import SamplerConfig

    mesh = make_mesh(MeshConfig(tp=2))
    state = train_init(SPEC, mesh, seed=2)
    save_checkpoint(str(tmp_path / "ckpt"), state)

    params = restore_params(str(tmp_path / "ckpt"), SPEC, mesh)
    eng = InferenceEngine(SPEC, mesh, params=jax.tree.map(np.asarray, params))
    out = eng.generate([5, 6, 7], max_new_tokens=6,
                       sampler=SamplerConfig(temperature=0.0))
    assert len(out.token_ids) == 6
    # and it really is the trained weights: logits match the state's params
    from quorum_tpu.models.transformer import forward_logits

    import jax.numpy as jnp

    toks = jnp.asarray([[5, 6, 7]], jnp.int32)
    a = np.asarray(forward_logits(state.params, SPEC, toks), np.float32)
    b = np.asarray(forward_logits(eng.params, SPEC, toks), np.float32)
    np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2)


def test_optimizer_recipe_schedule_clip_accumulation():
    """The shipped optimizer recipe (make_optimizer): warmup-cosine LR,
    global-norm clipping, and gradient accumulation. Accumulation is the
    TPU-relevant lever — accum_steps micro-batches must equal ONE step on
    the concatenated batch (optax.MultiSteps averages the window), so
    global batch scales in steps instead of HBM."""
    from quorum_tpu.training.trainer import make_optimizer

    spec = resolve_spec("llama-tiny", {"max_seq": "32"})
    mesh = make_mesh(MeshConfig())
    tokens = (np.arange(4 * 16, dtype=np.int32) % 97 + 3).reshape(4, 16)

    # One big-batch step…
    big = train_init(spec, mesh, seed=0,
                     optimizer=make_optimizer(grad_clip=1.0))
    big_step = make_train_step(spec, mesh,
                               optimizer=make_optimizer(grad_clip=1.0))
    big, _ = big_step(big, tokens)

    # …equals two accumulated half-batch micro-steps.
    acc_opt = make_optimizer(grad_clip=1.0, accum_steps=2)
    acc = train_init(spec, mesh, seed=0, optimizer=acc_opt)
    acc_step = make_train_step(spec, mesh, optimizer=acc_opt)
    acc, _ = acc_step(acc, tokens[:2])
    # materialize before the next (donating) step deletes the buffers
    mid = [np.asarray(x) for x in jax.tree.leaves(acc.params)]
    # the running mean must accumulate in f32 (bf16 would round away late
    # micro-batches as the window grows)
    acc_grads = [x for x in jax.tree.leaves(acc.opt_state)
                 if hasattr(x, "dtype") and x.ndim > 0]
    assert any(x.dtype == np.float32 for x in acc_grads)
    acc, _ = acc_step(acc, tokens[2:])

    base = [np.asarray(x, np.float32)
            for x in jax.tree.leaves(train_init(spec, mesh, seed=0).params)]

    def max_delta(params, ref):
        return max(float(np.abs(np.asarray(a, np.float32) - b).max())
                   for a, b in zip(jax.tree.leaves(params), ref))

    assert max_delta(mid, base) == 0.0  # first micro-step: no update applied
    for a, b in zip(jax.tree.leaves(acc.params), jax.tree.leaves(big.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-2, atol=1e-3)  # bf16 params: ±1 ulp

    # Warmup schedule: step-0 LR is ~0, so params barely move.
    warm_opt = make_optimizer(warmup_steps=10, total_steps=100)
    warm = train_init(spec, mesh, seed=0, optimizer=warm_opt)
    warm_step = make_train_step(spec, mesh, optimizer=warm_opt)
    warm, _ = warm_step(warm, tokens)
    assert max_delta(warm.params, base) < max_delta(big.params, base) / 10

    import pytest as _pytest
    with _pytest.raises(ValueError, match="warmup_steps"):
        make_optimizer(warmup_steps=100, total_steps=50)
