"""The tunnel watcher's commit discipline (scripts/tunnel_watch.py).

commit_onchip is the step that banks the round's most important artifact;
its rules get real-git pins: commit ONLY the artifact (never sweep the
operator's staged files — ADVICE r4), only when THIS session refreshed it,
and only when it carries actual measurements.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

# Multi-process / real-git: slow tier.
pytestmark = pytest.mark.slow


def _load():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "tunnel_watch.py")
    spec = importlib.util.spec_from_file_location("tunnel_watch", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _git(repo, *argv):
    return subprocess.run(["git", "-C", str(repo), *argv],
                          capture_output=True, text=True, check=True)


@pytest.fixture()
def repo(tmp_path):
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "config", "user.email", "t@t")
    _git(tmp_path, "config", "user.name", "t")
    (tmp_path / "base.txt").write_text("base\n")
    _git(tmp_path, "add", "base.txt")
    _git(tmp_path, "commit", "-q", "-m", "base")
    return tmp_path


def test_commit_scoped_to_artifact_only(repo, monkeypatch):
    """ADVICE r4: files the operator had staged must NOT be swept into the
    ONCHIP commit."""
    mod = _load()
    monkeypatch.setattr(mod, "REPO", str(repo))
    monkeypatch.setattr(mod, "ONCHIP", str(repo / "ONCHIP.json"))
    # operator's unrelated staged work
    (repo / "wip.txt").write_text("do not sweep\n")
    _git(repo, "add", "wip.txt")
    (repo / "ONCHIP.json").write_text(json.dumps(
        {"onchip_error": None, "onchip_started_ts": 5.0,
         "b7_decode_tok_s": 34.6}))
    assert mod.commit_onchip(started_after=0.0) is True
    shown = _git(repo, "show", "--name-only", "--format=", "HEAD").stdout
    assert shown.split() == ["ONCHIP.json"]
    # the operator's staged file is still staged, not committed
    status = _git(repo, "status", "--short").stdout
    assert "A  wip.txt" in status


def test_no_commit_without_measurements_or_freshness(repo, monkeypatch):
    mod = _load()
    monkeypatch.setattr(mod, "REPO", str(repo))
    onchip = repo / "ONCHIP.json"
    monkeypatch.setattr(mod, "ONCHIP", str(onchip))
    head = _git(repo, "rev-parse", "HEAD").stdout

    # error-only artifact (dead-at-start session): no commit
    onchip.write_text(json.dumps(
        {"onchip_error": "tunnel dead at session start", "ts": 5.0}))
    assert mod.commit_onchip(started_after=0.0) is False
    # headline sentinels are not measurements either
    onchip.write_text(json.dumps(
        {"value": -1.0, "vs_baseline": 0.0, "onchip_started_ts": 5.0}))
    assert mod.commit_onchip(started_after=0.0) is False
    # real measurements but STALE (mtime predates the session): no commit
    onchip.write_text(json.dumps({"b7_decode_tok_s": 34.6}))
    mtime = os.stat(onchip).st_mtime
    assert mod.commit_onchip(started_after=mtime + 1) is False
    assert _git(repo, "rev-parse", "HEAD").stdout == head
