"""Ulysses sequence parallelism (parallel/ulysses.py): the all-to-all SP
strategy beside the ring — head↔sequence all-to-alls, full-sequence local
attention. Must match the dense path exactly (same contract as the ring
tests), support sliding-window specs (the ring's documented gap), and serve
through the engine via ``sp_impl=ulysses``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.ops.attention import prefill_attention
from quorum_tpu.ops.sampling import SamplerConfig
from quorum_tpu.parallel import MeshConfig, make_mesh
from quorum_tpu.parallel.ulysses import ulysses_prefill_attention

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def _rand(seed, shape):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)


@pytest.mark.parametrize("cfg,h,n_kv,window", [
    (MeshConfig(dp=2, sp=2, tp=2), 8, 4, 0),
    (MeshConfig(sp=4), 8, 4, 0),
    (MeshConfig(dp=2, sp=2, tp=2), 8, 4, 16),   # ring can't do this
    (MeshConfig(sp=2, tp=4), 8, 2, 0),          # KV heads < tp: replicate
])
def test_matches_dense(cfg, h, n_kv, window):
    mesh = make_mesh(cfg)
    b, s, hd = 2, 64, 16
    q, k, v = (_rand(i, (b, hh, s, hd))
               for i, hh in ((0, h), (1, n_kv), (2, n_kv)))
    lengths = jnp.asarray([64, 37], jnp.int32)
    out = np.asarray(ulysses_prefill_attention(
        q, k, v, lengths, mesh, window=window))
    ref = np.asarray(prefill_attention(q, k, v, lengths, window=window))
    # compare only valid rows (padded queries are garbage on both sides)
    for r, n in enumerate(np.asarray(lengths)):
        np.testing.assert_allclose(out[r, :, :n], ref[r, :, :n],
                                   rtol=2e-5, atol=2e-5)


def test_indivisible_shapes_fall_back():
    mesh = make_mesh(MeshConfig(sp=8))
    q, k, v = (_rand(i, (1, hh, 24, 16)) for i, hh in ((0, 4), (1, 4), (2, 4)))
    lengths = jnp.asarray([24], jnp.int32)  # 24 % 8 != 0 → dense fallback
    out = np.asarray(ulysses_prefill_attention(q, k, v, lengths, mesh))
    ref = np.asarray(prefill_attention(q, k, v, lengths))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_engine_serves_through_ulysses():
    """sp_impl=ulysses admission matches the single-device engine — with a
    WINDOWED spec, which ring-based sp rejects outright."""
    spec = resolve_spec("llama-tiny",
                        {"n_kv_heads": "4", "sliding_window": "16"})
    prompt = [(5 + 3 * i) % 500 for i in range(60)]
    eng_1 = InferenceEngine(spec, decode_chunk=4, n_slots=2)
    eng_sp = InferenceEngine(spec, make_mesh(MeshConfig(sp=2, tp=2)),
                             decode_chunk=4, n_slots=2, sp_impl="ulysses")
    assert eng_sp._use_sp
    for sampler, seed in ((SamplerConfig(temperature=0.0), 0),
                          (SamplerConfig(temperature=0.8, top_p=0.9), 7)):
        one = eng_1.generate(prompt, max_new_tokens=10, sampler=sampler,
                             seed=seed).token_ids
        sp_toks = eng_sp.generate(prompt, max_new_tokens=10, sampler=sampler,
                                  seed=seed).token_ids
        assert sp_toks == one


def test_backend_url_and_validation():
    import asyncio

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    b = TpuBackend.from_spec(BackendSpec(
        name="u",
        url="tpu://llama-tiny?n_kv_heads=4&sp=2&tp=2&sp_impl=ulysses&seed=2",
        model="t"))
    assert b.engine._use_sp and b.engine.sp_impl == "ulysses"
    body = {"model": "t",
            "messages": [{"role": "user", "content": "hello " * 30}],
            "max_tokens": 6}
    res = asyncio.run(b.complete(body, {}, timeout=120))
    assert res.status_code == 200

    with pytest.raises(ValueError, match="sp_impl"):
        InferenceEngine(resolve_spec("llama-tiny", {"n_kv_heads": "4"}),
                        sp_impl="bogus")
    # statically-unsupported head counts fail at construction, not with a
    # silent dense fallback at serving time
    with pytest.raises(ValueError, match="head counts"):
        InferenceEngine(resolve_spec("llama-tiny", {"n_kv_heads": "4"}),
                        make_mesh(MeshConfig(sp=8)), sp_impl="ulysses")
    # windowed + ring sp is still rejected, and the error names the fix
    with pytest.raises(ValueError, match="ulysses"):
        InferenceEngine(
            resolve_spec("llama-tiny",
                         {"n_kv_heads": "4", "sliding_window": "16"}),
            make_mesh(MeshConfig(sp=2)))
