"""Zero-drain continuous batching (``zero_drain=0|1``, ISSUE 11).

Fast tier: knob parsing/validation, the drain-based cache-key pin
(zero_drain off compiles the EXACT pre-existing program variants — no
staging state, single-shot admission for short prompts), a colocated
smoke pinned token-for-token against the drain-based engine with live
reap-boundary injection (admission registers onto a live ring,
``admission_overlap_total`` > 0, ``admission_stall_seconds_total``
structurally 0), the injection-path fault containment contract (a failed
``engine.admit``/``engine.prefill_segment`` dooms ONLY the injecting
request — staging is the blast-radius boundary, exactly like a disagg
prefill fault), and the drain-based engine's stall accounting (the
retired C=1/K=1 coupling is measurable where it still applies).

Slow tier: the full acceptance pins at ``decode_pipeline=4 ×
decode_loop=4`` across the greedy / sampled / EOS-mid-chunk /
constrained / members / spec / prefix-restore legs, each against the
drain-based engine.
"""

import asyncio

import numpy as np
import pytest

from quorum_tpu import faults
from quorum_tpu.analysis import budget
from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.ops.sampling import SamplerConfig

TINY = resolve_spec("llama-tiny", {"n_kv_heads": "4"})
SAMPLED = SamplerConfig(temperature=0.8, top_p=0.9)
GREEDY = SamplerConfig(temperature=0.0)


def _gen(eng, prompt, seed=0, n=8, sampler=SAMPLED, **kw):
    return eng.generate(prompt, max_new_tokens=n, sampler=sampler,
                        seed=seed, **kw).token_ids


# ---- fast: config validation ------------------------------------------------


def test_zero_drain_engine_validation():
    # zero_drain rides chunked prefill; an engine without it must reject
    with pytest.raises(ValueError, match="chunked prefill"):
        InferenceEngine(TINY, prefill_chunk=0, zero_drain=True)


def test_zero_drain_url_knob_validation():
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    def build(url):
        return TpuBackend.from_spec(
            BackendSpec(name="t", url=url, model="m"))

    for url, frag in [
        ("tpu://llama-tiny?zero_drain=1&disagg=1+1", "zero_drain=1 does"),
        ("tpu://llama-tiny?zero_drain=1&prefill_chunk=0",
         "chunked prefill"),
        ("tpu://llama-tiny?zero_drain=maybe", "zero_drain"),
    ]:
        with pytest.raises(ValueError, match=frag):
            build(url)


# ---- fast: drain-based cache-key pin + smoke --------------------------------


@pytest.fixture(scope="module")
def smoke_engines():
    """One drain-based + one zero_drain engine over identical knobs,
    shared by the fast smoke tests (compiles once per module)."""
    kw = dict(decode_chunk=4, n_slots=2, decode_pipeline=2,
              prefill_chunk=16, seed=11300)
    eng_c = InferenceEngine(TINY, **kw)
    eng_z = InferenceEngine(TINY, zero_drain=True, **kw)
    yield eng_c, eng_z
    eng_c.shutdown()
    eng_z.shutdown()


def test_drain_based_compiles_exact_preexisting_variants(smoke_engines):
    """zero_drain off = byte-for-byte the old engine: no staging cache,
    no injection program variants, single-shot admission for short
    prompts, and the unconstrained decode programs under their exact
    pre-existing 3-tuple keys."""
    eng_c, _ = smoke_engines
    _gen(eng_c, [3, 4, 5], seed=1)
    assert not eng_c.zero_drain and not eng_c.staged
    assert eng_c.prefill_params is None
    assert not hasattr(eng_c, "_sck")
    # program families against the shared budget (classifying also pins
    # each key's exact shape — analysis/compile_budget.json)
    assert budget.admit_families(eng_c._admit_cache) == {"single_shot"}
    assert budget.decode_families(eng_c._decode_cache) == {"plain"}
    # one end-to-end literal sentinel: the plain decode key is still the
    # pre-existing (n_steps, want_lp, history) 3-tuple
    assert any(isinstance(k, tuple) and len(k) == 3
               and isinstance(k[0], int) for k in eng_c._decode_cache)
    assert eng_c.n_admission_overlap == 0
    assert eng_c.metrics()["zero_drain"] == 0


def test_zero_drain_smoke_pinned_with_live_injection(smoke_engines):
    """Greedy and sampled streams (short AND multi-segment prompts) equal
    the drain-based engine token for token, with every admission riding
    the staged seg→inject→register path on ONE device group (zero
    handoff bytes — nothing crosses a group boundary)."""
    eng_c, eng_z = smoke_engines
    long_p = [(3 + 5 * i) % 500 for i in range(40)]
    legs = [([3, 4, 5], GREEDY, 0), ([7, 8, 9], SAMPLED, 11),
            (long_p, SAMPLED, 3)]
    for prompt, sampler, seed in legs:
        assert (_gen(eng_z, prompt, seed=seed, sampler=sampler)
                == _gen(eng_c, prompt, seed=seed, sampler=sampler))
    # one group: injection moves no bytes across any boundary
    assert eng_z.n_kv_handoffs == 0 and eng_z.kv_handoff_bytes == 0
    # never a single-shot admit program; every admission rides
    # seg+inject+register (compile_budget.json gates)
    fams = budget.admit_families(eng_z._admit_cache)
    assert "single_shot" not in fams
    assert {"seg", "register", "hslice", "hput"} <= fams, fams
    m = eng_z.metrics()
    assert m["zero_drain"] == 1 and m["disagg"] == 0
    # the structural contract: the ring NEVER clamped for an admission
    assert m["admission_stall_seconds_total"] == 0.0
    with eng_z._cond:
        assert eng_z._admission_pressure() is False
    h = eng_z.health()
    assert h["scheduler_alive"] and h["prefill_scheduler_alive"]


def test_zero_drain_injection_overlaps_live_ring(smoke_engines):
    """Two concurrent streams: the second's staged admission registers
    while the first decodes at full ring depth — admission_overlap_total
    advances and the stall counter stays structurally 0."""
    _, eng_z = smoke_engines
    over0 = eng_z.n_admission_overlap
    a = eng_z.submit([9, 8, 7], max_new_tokens=40, sampler=GREEDY)
    b = eng_z.submit([5, 6, 7], max_new_tokens=40, sampler=GREEDY)
    ta = list(eng_z.stream_results(a))
    tb = list(eng_z.stream_results(b))
    assert len(ta) == 40 and len(tb) == 40
    assert eng_z.n_admission_overlap > over0
    assert eng_z.admission_stall_s == 0.0


def test_zero_drain_injection_fault_dooms_only_its_request(smoke_engines):
    """The injection path's containment: a prefill-segment failure while
    other rows decode dooms only the injecting request — the queued
    bystander completes unchanged, nothing is requeued, no device-state
    rebuild (staging is the blast-radius boundary)."""
    eng_c, eng_z = smoke_engines
    base = _gen(eng_z, [3, 4, 5], seed=1)
    assert base == _gen(eng_c, [3, 4, 5], seed=1)
    rebuilds0 = eng_z.n_rebuilds
    faults.arm("engine.prefill_segment", times=1)
    try:
        bad = eng_z.submit([5, 6, 7], max_new_tokens=8, sampler=SAMPLED,
                           seed=2)
        bystander = eng_z.submit([3, 4, 5], max_new_tokens=8,
                                 sampler=SAMPLED, seed=1)
        with pytest.raises(faults.FaultInjected):
            list(eng_z.stream_results(bad))
        assert list(eng_z.stream_results(bystander)) == base
    finally:
        faults.disarm()
    assert _gen(eng_z, [3, 4, 5], seed=1) == base
    assert eng_z.n_rebuilds == rebuilds0  # staging survived: no rebuild
    assert eng_z.health()["scheduler_alive"]


def test_drain_based_engine_accumulates_admission_stall():
    """The coupling zero_drain retires is measurable where it still
    applies: a chunked admission under a live stream clamps the K=4·C=4
    ring to depth 1 across consecutive turns, and the stall counter
    records the window. (The zero_drain twin of this scenario is pinned
    to 0.0 in the smoke above.)"""
    eng = InferenceEngine(TINY, decode_chunk=4, n_slots=2,
                          decode_pipeline=4, decode_loop=4,
                          prefill_chunk=16, seed=11310)
    try:
        churn_p = [(7 + 3 * i) % 500 for i in range(48)]
        eng.generate([9, 8, 7], max_new_tokens=8, sampler=GREEDY)  # warm
        eng.generate(churn_p, max_new_tokens=2, sampler=GREEDY)
        # distinct churn prompt per admission — a repeat would tier-0
        # reuse its resident prefix and shrink the clamp window
        churn2 = [(11 + 5 * i) % 500 for i in range(48)]
        pre = eng.submit(churn2, max_new_tokens=2, sampler=GREEDY)
        stream = eng.submit([9, 8, 7], max_new_tokens=256, sampler=GREEDY)
        list(eng.stream_results(stream))
        list(eng.stream_results(pre))
        assert eng.admission_stall_s > 0.0
        assert eng.metrics()["admission_stall_seconds_total"] > 0.0
        assert eng.n_admission_overlap == 0  # drain-based: structurally 0
    finally:
        eng.shutdown()


# ---- slow: acceptance legs at K=4·C=4 ---------------------------------------


@pytest.fixture(scope="module")
def accept_engines():
    """Drain-based vs zero_drain at decode_pipeline=4 × decode_loop=4
    (the deep-fused acceptance shape)."""
    kw = dict(decode_chunk=4, n_slots=2, decode_pipeline=4, decode_loop=4,
              prefill_chunk=16, seed=11320)
    eng_c = InferenceEngine(TINY, **kw)
    eng_z = InferenceEngine(TINY, zero_drain=True, **kw)
    yield eng_c, eng_z
    eng_c.shutdown()
    eng_z.shutdown()


@pytest.mark.slow
def test_zero_drain_greedy_sampled_chunked_pin(accept_engines):
    eng_c, eng_z = accept_engines
    long_p = [(3 + 5 * i) % 500 for i in range(40)]
    for prompt, sampler, seed in [([3, 4, 5], GREEDY, 0),
                                  ([7, 8, 9], SAMPLED, 11),
                                  (long_p, SAMPLED, 3)]:
        assert (_gen(eng_z, prompt, seed=seed, n=12, sampler=sampler)
                == _gen(eng_c, prompt, seed=seed, n=12, sampler=sampler))
    assert eng_z.admission_stall_s == 0.0


@pytest.mark.slow
def test_zero_drain_eos_mid_chunk_pin(accept_engines):
    """A row finishing ON DEVICE mid-megachunk (EOS at a non-boundary
    position) retires identically on both engines — finish_reason stop,
    zero overrun at any K·C."""
    eng_c, eng_z = accept_engines
    probe = _gen(eng_c, [5, 6, 7], seed=2, n=12)
    eos = next((t for i, t in enumerate(probe)
                if i >= 4 and i % 4 != 3 and t not in probe[:i]), None)
    assert eos is not None, probe
    over0 = eng_z.n_overrun
    r_z = eng_z.generate([5, 6, 7], max_new_tokens=12, sampler=SAMPLED,
                         seed=2, eos_id=eos)
    r_c = eng_c.generate([5, 6, 7], max_new_tokens=12, sampler=SAMPLED,
                         seed=2, eos_id=eos)
    assert r_z.token_ids == r_c.token_ids
    assert r_z.finish_reason == r_c.finish_reason == "stop"
    assert eng_z.n_overrun == over0


@pytest.mark.slow
def test_zero_drain_constrained_pin():
    """response_format JSON mode through the full backend: the zero-drain
    engine's constrained stream (grammar placed at register time in the
    injection drain, DFA state installed by the register program) equals
    the drain-based engine's byte for byte."""
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    def build(url):
        return TpuBackend.from_spec(BackendSpec(name="t", url=url,
                                                model="m"))

    opts = ("n_kv_heads=4&seed=11330&decode_pipeline=4&decode_loop=4"
            "&prefill_chunk=16&decode_chunk=4&slots=2")
    b_z = build(f"tpu://llama-tiny?{opts}&zero_drain=1")
    b_c = build(f"tpu://llama-tiny?{opts}")
    body = {"model": "m", "max_tokens": 24, "temperature": 0.0, "seed": 3,
            "messages": [{"role": "user", "content": "json please"}],
            "response_format": {"type": "json_object"}}

    async def run(b):
        res = await b.complete(dict(body), {}, timeout=300)
        return res.body["choices"][0]["message"]["content"]

    assert asyncio.run(run(b_z)) == asyncio.run(run(b_c))
    assert b_z.engine.n_constrained >= 1
    assert b_z.engine is not b_c.engine  # structural key split


@pytest.mark.slow
def test_zero_drain_members_pin():
    """members=M under zero_drain: each member's stream equals the
    members=1 engine with that member's seed — the member-stacked staging
    cache and the member-aware injection slice/write address the right
    flat rows."""
    eng_m = InferenceEngine(TINY, members=2, zero_drain=True,
                            decode_chunk=4, n_slots=2, decode_pipeline=4,
                            decode_loop=4, prefill_chunk=16, seed=0)
    singles = [InferenceEngine(TINY, seed=i, decode_chunk=4, n_slots=2)
               for i in range(2)]
    try:
        want = [_gen(singles[i], [3, 4, 5], seed=9, n=6) for i in range(2)]
        got = [_gen(eng_m, [3, 4, 5], seed=9, n=6, member=i)
               for i in range(2)]
        assert got == want
    finally:
        eng_m.shutdown()
        for e in singles:
            e.shutdown()


@pytest.mark.slow
def test_zero_drain_spec_decode_pin():
    """Speculative decoding composes: a forced-periodic stream speculates
    on both engines (ring-resident verify turns entering the same ring
    the injections land on) and the zero-drain stream equals the
    drain-based one token for token."""
    kw = dict(decode_chunk=4, n_slots=2, decode_pipeline=4,
              prefill_chunk=16, spec_decode=4, seed=11340)
    eng_c = InferenceEngine(TINY, **kw)
    eng_z = InferenceEngine(TINY, zero_drain=True, **kw)
    try:
        bias = np.zeros((TINY.vocab_size,), np.float32)
        bias[7] = 1e9

        def run(eng):
            req = eng.submit([7, 7, 7, 7], max_new_tokens=16,
                             sampler=GREEDY, logit_bias=bias)
            return list(eng.stream_results(req))

        assert run(eng_z) == run(eng_c)
        assert eng_z.n_spec_turns > 0
        assert eng_z.admission_stall_s == 0.0
    finally:
        eng_c.shutdown()
        eng_z.shutdown()


@pytest.mark.slow
def test_zero_drain_prefix_restore_pin():
    """prefix_store=host under zero_drain: a churn-evicted conversation's
    follow-up restores host→STAGING, rides the tail prefill at an offset,
    and injects the whole prefix into the decode slot — still equal to a
    cold drain-based prefill token for token."""
    eng_z = InferenceEngine(TINY, zero_drain=True, decode_chunk=4,
                            n_slots=1, prefill_chunk=16,
                            prefix_store="host", prefix_store_chunk=16,
                            seed=11350)
    eng_c = InferenceEngine(TINY, decode_chunk=4, n_slots=1,
                            prefill_chunk=16, seed=11350)
    try:
        conv = [(3 + 5 * i) % 500 for i in range(33)]
        other = [(9 + 7 * i) % 500 for i in range(33)]
        out1 = _gen(eng_z, conv, seed=4, n=6)
        eng_z.drain_prefix_store()
        _gen(eng_z, other, seed=5, n=6)  # churn the single slot
        eng_z.drain_prefix_store()
        follow = conv + out1 + [17, 19]
        assert (_gen(eng_z, follow, seed=6, n=6)
                == _gen(eng_c, follow, seed=6, n=6))
        assert eng_z.prefix_store_hits >= 1
        assert eng_z.prefix_store_tokens_restored > 0
    finally:
        eng_z.shutdown()
        eng_c.shutdown()
